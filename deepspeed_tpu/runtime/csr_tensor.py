"""CSR (row-sparse) tensor for embedding gradients.

Reference behavior: deepspeed/runtime/csr_tensor.py:11-59 + the engine's
sparse all-gather of embedding grads (engine.py:187-193,1227-1265): an
embedding gradient is nonzero only on the rows whose tokens appeared in the
batch, so exchanging (row_indices, row_values) beats a dense all-reduce.

TPU notes: inside the jitted step XLA already keeps the embedding gradient
as a fused scatter-add (no dense S x V matrix materializes), so the compute
path needs no CSR. This structure serves the host/comm side — compressed
checkpoint deltas and DCN-friendly gradient exchange — and keeps API parity
(`sparse_gradients` config). Row extraction is jit-compatible when given a
static row capacity.
"""
from typing import Optional

import numpy as np


class CSRTensor:
    """Row-sparse view: indices (nnz_rows,), values (nnz_rows, row_dim)."""

    def __init__(self, indices, values, dense_size):
        self.indices = indices
        self.values = values
        self.dense_size = tuple(dense_size)

    @staticmethod
    def from_dense(dense, max_rows: Optional[int] = None):
        """Extract nonzero rows. With `max_rows` the result has static
        shapes (jit-friendly): indices padded with -1, values with zeros."""
        import jax.numpy as jnp

        dense = jnp.asarray(dense)
        row_nonzero = jnp.any(dense != 0, axis=tuple(range(1, dense.ndim)))
        if max_rows is None:
            idx = np.flatnonzero(np.asarray(row_nonzero))
            return CSRTensor(jnp.asarray(idx), dense[idx], dense.shape)
        order = jnp.argsort(~row_nonzero)          # nonzero rows first
        idx = order[:max_rows]
        valid = row_nonzero[idx]
        values = jnp.where(valid[:, None] if dense.ndim == 2 else valid,
                           dense[idx], 0)
        indices = jnp.where(valid, idx, -1)
        return CSRTensor(indices, values, dense.shape)

    def to_dense(self):
        import jax.numpy as jnp

        out = jnp.zeros(self.dense_size, self.values.dtype)
        valid = self.indices >= 0
        safe = jnp.maximum(self.indices, 0)
        vals = jnp.where(valid[:, None] if self.values.ndim == 2 else valid,
                         self.values, 0)
        return out.at[safe].add(vals)

    def sparse_size(self):
        """(#stored elements, #dense elements) — reference csr_tensor.py:47."""
        stored = int(np.prod(self.values.shape))
        dense = int(np.prod(self.dense_size))
        return stored, dense

    def add(self, other: "CSRTensor") -> "CSRTensor":
        """Merge two row-sparse grads (used when combining DP shards)."""
        assert self.dense_size == other.dense_size
        import jax.numpy as jnp

        return CSRTensor.from_dense(self.to_dense() + other.to_dense())

    def __repr__(self):
        return (f"CSRTensor(indices={np.asarray(self.indices).tolist()}, "
                f"dense_size={self.dense_size})")


def allgather_csr(csr: CSRTensor, axis_name: str):
    """Exchange row-sparse grads over a mesh axis and sum (the reference's
    sparse_allreduce_and_scatter, engine.py:1227-1253). Call inside
    shard_map with static row capacity."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    all_idx = lax.all_gather(csr.indices, axis_name)     # (W, rows)
    all_val = lax.all_gather(csr.values, axis_name)      # (W, rows, dim)
    # declare the accumulator varying over the axis so the fori_loop carry
    # type is stable under shard_map's VMA checking
    out = lax.pcast(jnp.zeros(csr.dense_size, csr.values.dtype),
                    (axis_name,), to="varying")
    W = all_idx.shape[0]

    def body(w, out):
        idx = all_idx[w]
        valid = idx >= 0
        safe = jnp.maximum(idx, 0)
        vals = jnp.where(valid[:, None] if all_val.ndim == 3 else valid,
                         all_val[w], 0)
        return out.at[safe].add(vals)

    return lax.fori_loop(0, W, body, out)
