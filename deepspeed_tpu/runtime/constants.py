"""ds_config JSON key names and defaults.

Key-for-key parity with the reference config surface (reference:
deepspeed/runtime/constants.py) so existing ds_config.json files work unchanged.
TPU-specific extensions are marked at the bottom.
"""

#############################################
# Routes
#############################################
ROUTE_TRAIN = "train"
ROUTE_EVAL = "eval"
ROUTE_PREDICT = "predict"
ROUTE_ENCODE = "encode"

#############################################
# Batch size
#############################################
TRAIN_BATCH_SIZE = "train_batch_size"
TRAIN_BATCH_SIZE_DEFAULT = None

TRAIN_MICRO_BATCH_SIZE_PER_GPU = "train_micro_batch_size_per_gpu"
TRAIN_MICRO_BATCH_SIZE_PER_GPU_DEFAULT = None

GRADIENT_ACCUMULATION_STEPS = "gradient_accumulation_steps"
GRADIENT_ACCUMULATION_STEPS_DEFAULT = None

SPARSE_GRADIENTS = "sparse_gradients"
SPARSE_GRADIENTS_DEFAULT = False

#############################################
# Optimizer / scheduler
#############################################
OPTIMIZER = "optimizer"
OPTIMIZER_TYPE_DEFAULT = None
OPTIMIZER_PARAMS = "params"
TYPE = "type"
LEGACY_FUSION = "legacy_fusion"
LEGACY_FUSION_DEFAULT = False

SCHEDULER = "scheduler"
SCHEDULER_TYPE_DEFAULT = None
SCHEDULER_PARAMS = "params"
MAX_GRAD_NORM = "max_grad_norm"

# optimizer type names (reference: runtime/config.py:29-41)
ADAM_OPTIMIZER = "adam"
ADAMW_OPTIMIZER = "adamw"
LAMB_OPTIMIZER = "lamb"
ONEBIT_ADAM_OPTIMIZER = "onebitadam"
# 0/1 Adam (arxiv 2202.06009): variance freeze + 1-bit wire + local steps
ZEROONE_ADAM_OPTIMIZER = "zerooneadam"
# extension: sgd and adafactor are also built-in on the TPU build
SGD_OPTIMIZER = "sgd"
ADAFACTOR_OPTIMIZER = "adafactor"
DEEPSPEED_OPTIMIZERS = [ADAM_OPTIMIZER, ADAMW_OPTIMIZER, LAMB_OPTIMIZER,
                        ONEBIT_ADAM_OPTIMIZER, ZEROONE_ADAM_OPTIMIZER,
                        SGD_OPTIMIZER, ADAFACTOR_OPTIMIZER]

#############################################
# ZeRO optimization (top-level key lives in zero/constants.py)
#############################################
ZERO_ALLOW_UNTESTED_OPTIMIZER = "zero_allow_untested_optimizer"
ZERO_ALLOW_UNTESTED_OPTIMIZER_DEFAULT = False

#############################################
# FP16 / mixed precision
#############################################
FP16 = "fp16"
FP16_ENABLED = "enabled"
FP16_ENABLED_DEFAULT = False
FP16_LOSS_SCALE = "loss_scale"
FP16_LOSS_SCALE_DEFAULT = 0
FP16_INITIAL_SCALE_POWER = "initial_scale_power"
FP16_INITIAL_SCALE_POWER_DEFAULT = 32
FP16_LOSS_SCALE_WINDOW = "loss_scale_window"
FP16_LOSS_SCALE_WINDOW_DEFAULT = 1000
FP16_HYSTERESIS = "hysteresis"
FP16_HYSTERESIS_DEFAULT = 2
FP16_MIN_LOSS_SCALE = "min_loss_scale"
FP16_MIN_LOSS_SCALE_DEFAULT = 1

# apex AMP passthrough (accepted, mapped onto bf16 on TPU)
AMP = "amp"
AMP_ENABLED = "enabled"
AMP_ENABLED_DEFAULT = False

#############################################
# Gradient clipping / prescaling
#############################################
GRADIENT_CLIPPING = "gradient_clipping"
GRADIENT_CLIPPING_DEFAULT = 0.0

PRESCALE_GRADIENTS = "prescale_gradients"
PRESCALE_GRADIENTS_DEFAULT = False

GRADIENT_PREDIVIDE_FACTOR = "gradient_predivide_factor"
GRADIENT_PREDIVIDE_FACTOR_DEFAULT = 1.0

#############################################
# Steps / logging
#############################################
STEPS_PER_PRINT = "steps_per_print"
STEPS_PER_PRINT_DEFAULT = 10

DISABLE_ALLGATHER = "disable_allgather"
DISABLE_ALLGATHER_DEFAULT = False

DUMP_STATE = "dump_state"
DUMP_STATE_DEFAULT = False

WALL_CLOCK_BREAKDOWN = "wall_clock_breakdown"
WALL_CLOCK_BREAKDOWN_DEFAULT = False

MEMORY_BREAKDOWN = "memory_breakdown"
MEMORY_BREAKDOWN_DEFAULT = False

#############################################
# Tensorboard
#############################################
TENSORBOARD = "tensorboard"
TENSORBOARD_ENABLED = "enabled"
TENSORBOARD_ENABLED_DEFAULT = False
TENSORBOARD_OUTPUT_PATH = "output_path"
TENSORBOARD_OUTPUT_PATH_DEFAULT = ""
TENSORBOARD_JOB_NAME = "job_name"
TENSORBOARD_JOB_NAME_DEFAULT = "DeepSpeedJobName"

#############################################
# Sparse attention
#############################################
SPARSE_ATTENTION = "sparse_attention"
SPARSE_ATTENTION_MODE = "mode"
SPARSE_ATTENTION_MODE_DEFAULT = "fixed"
SPARSE_DENSE_MODE = "dense"
SPARSE_FIXED_MODE = "fixed"
SPARSE_VARIABLE_MODE = "variable"
SPARSE_BIGBIRD_MODE = "bigbird"
SPARSE_BSLONGFORMER_MODE = "bslongformer"

#############################################
# Progressive layer drop
#############################################
PROGRESSIVE_LAYER_DROP = "progressive_layer_drop"
PLD_ENABLED = "enabled"
PLD_ENABLED_DEFAULT = False
PLD_THETA = "theta"
PLD_THETA_DEFAULT = 1.0
PLD_GAMMA = "gamma"
PLD_GAMMA_DEFAULT = 0.001

#############################################
# Gradient-accumulation dtype / misc
#############################################
ALLREDUCE_ALWAYS_FP32 = "fp32_allreduce"
ALLREDUCE_ALWAYS_FP32_DEFAULT = False

#############################################
# TPU extensions (not in reference)
#############################################
BF16 = "bf16"                       # {"enabled": true} — native TPU dtype
BF16_ENABLED = "enabled"
BF16_ENABLED_DEFAULT = False

MESH = "mesh"                       # {"data": -1, "model": 1, "pipe": 1}
MESH_DATA_AXIS = "data"
MESH_MODEL_AXIS = "model"
MESH_PIPE_AXIS = "pipe"
MESH_SEQ_AXIS = "seq"
MESH_ALLOW_PARTIAL = "allow_partial"   # opt-in: mesh may cover a device subset

#############################################
# Checkpoint (reference constants: "checkpoint": {"tag_validation": "Warn"})
#############################################
CHECKPOINT = "checkpoint"
CHECKPOINT_TAG_VALIDATION = "tag_validation"
CHECKPOINT_TAG_VALIDATION_DEFAULT = "Warn"
CHECKPOINT_TAG_VALIDATION_MODES = ["WARN", "IGNORE", "FAIL"]

#############################################
# Resilience (TPU extension): atomic checkpoints, auto-resume, watchdog
#############################################
RESILIENCE = "resilience"
RESILIENCE_ATOMIC = "atomic_checkpoints"        # temp-dir + manifest + rename
RESILIENCE_ATOMIC_DEFAULT = True
RESILIENCE_FSYNC = "fsync"                      # fsync payload + dirs on commit
RESILIENCE_FSYNC_DEFAULT = True
RESILIENCE_KEEP_TAGS = "keep_checkpoint_tags"   # retention; 0 = keep all
RESILIENCE_KEEP_TAGS_DEFAULT = 0
RESILIENCE_VERIFY_ON_LOAD = "verify_on_load"    # manifest replay before load
RESILIENCE_VERIFY_ON_LOAD_DEFAULT = True
RESILIENCE_AUTO_RESUME = "auto_resume"          # default for load_checkpoint
RESILIENCE_AUTO_RESUME_DEFAULT = False
# async checkpoint commit: payload write + streaming hash + fsync on a
# background commit thread; only the atomic rename + latest-pointer
# update stay on the training thread (emergency checkpoints are always
# synchronous).  Back-pressure: at most one commit in flight.
RESILIENCE_ASYNC_COMMIT = "async_commit"
RESILIENCE_ASYNC_COMMIT_DEFAULT = False

RESILIENCE_WATCHDOG = "watchdog"
WATCHDOG_ENABLED = "enabled"
WATCHDOG_ENABLED_DEFAULT = False
WATCHDOG_MAX_SKIPPED = "max_skipped_steps"      # overflow streak; 0 = off
WATCHDOG_MAX_SKIPPED_DEFAULT = 0
WATCHDOG_MAX_NAN = "max_nan_losses"             # NaN/Inf loss streak; 0 = off
WATCHDOG_MAX_NAN_DEFAULT = 0
WATCHDOG_STALL_TIMEOUT = "stall_timeout_seconds"  # wall-clock; 0 = off
WATCHDOG_STALL_TIMEOUT_DEFAULT = 0
WATCHDOG_ACTION = "action"                      # "abort" | "continue"
WATCHDOG_ACTION_DEFAULT = "abort"
WATCHDOG_EMERGENCY_DIR = "emergency_checkpoint_dir"  # None = last save_dir
WATCHDOG_EMERGENCY_DIR_DEFAULT = None

# resilience.supervisor sub-block: the self-healing training loop
# (runtime/resilience/supervisor.py) — failure detection windows and the
# bounded retry/backoff ladder.  All step-denominated (the supervisor
# runs on a step clock, so tests and benches are deterministic).
RESILIENCE_SUPERVISOR = "supervisor"
SUPERVISOR_HEARTBEAT_TIMEOUT = "heartbeat_timeout_steps"  # silence > N = dead
SUPERVISOR_HEARTBEAT_TIMEOUT_DEFAULT = 3
SUPERVISOR_MAX_TRANSIENT_RETRIES = "max_transient_retries"  # in-place retries
SUPERVISOR_MAX_TRANSIENT_RETRIES_DEFAULT = 2
SUPERVISOR_RETRY_BACKOFF = "retry_backoff_steps"  # backoff = this *
# (strike - 1): the FIRST retry is immediate, later strikes wait longer
SUPERVISOR_RETRY_BACKOFF_DEFAULT = 1
SUPERVISOR_MAX_RECOVERY_ATTEMPTS = "max_recovery_attempts"  # per incident
SUPERVISOR_MAX_RECOVERY_ATTEMPTS_DEFAULT = 3
SUPERVISOR_MAX_RESTARTS = "max_restarts"            # lifetime elastic restarts
SUPERVISOR_MAX_RESTARTS_DEFAULT = 4
SUPERVISOR_CHECKPOINT_EVERY = "checkpoint_every_steps"  # commit cadence; 0=off
SUPERVISOR_CHECKPOINT_EVERY_DEFAULT = 1

# resilience.integrity sub-block: silent-corruption defense (runtime/
# resilience/integrity.py, ISSUE 13) — device-side step sentinels with a
# host EMA/z-score window, cross-replica checksum vote, duplicate-compute
# sentinel micro-step.  Opt-in: the armed step jits carry extra (cheap)
# norm outputs, so the master switch defaults off and disarmed runs are
# bit-identical at zero extra compiles (tier-1 pin).
RESILIENCE_INTEGRITY = "integrity"
INTEGRITY_ENABLED = "enabled"                   # master switch
INTEGRITY_ENABLED_DEFAULT = False
INTEGRITY_WINDOW = "window"                     # EMA window, steps
INTEGRITY_WINDOW_DEFAULT = 32
INTEGRITY_Z_THRESHOLD = "z_threshold"           # |z| past this = anomaly
INTEGRITY_Z_THRESHOLD_DEFAULT = 6.0
INTEGRITY_MIN_HISTORY = "min_history"           # steps before z can fire
INTEGRITY_MIN_HISTORY_DEFAULT = 4
INTEGRITY_CONFIRM_STEPS = "confirm_steps"       # anomalous steps before a
# sentinel-only (no-culprit) corrupt verdict
INTEGRITY_CONFIRM_STEPS_DEFAULT = 2
INTEGRITY_CLEAR_STEPS = "clear_steps"           # normal steps that close
# an unconfirmed anomaly as a false positive
INTEGRITY_CLEAR_STEPS_DEFAULT = 2
INTEGRITY_VOTE_EVERY = "vote_every_steps"       # background vote; 0 = only
# on sentinel anomaly
INTEGRITY_VOTE_EVERY_DEFAULT = 16
INTEGRITY_DUP_CHECK_EVERY = "dup_check_every_steps"  # duplicate-compute
# sentinel micro-step cadence; 0 = off (costs one extra fwd+bwd)
INTEGRITY_DUP_CHECK_EVERY_DEFAULT = 0
INTEGRITY_QUARANTINE_AFTER = "quarantine_after"  # corrupt verdicts on one
# rank before the supervisor quarantines it (elastic restart without it)
INTEGRITY_QUARANTINE_AFTER_DEFAULT = 2

#############################################
# Telemetry (TPU extension): structured step tracing, unified metrics
# stream, measured-vs-analytic MFU accounting (deepspeed_tpu/telemetry/)
#############################################
TELEMETRY = "telemetry"
TELEMETRY_ENABLED = "enabled"                   # master switch
TELEMETRY_ENABLED_DEFAULT = False
TELEMETRY_TRACE = "trace"                       # span tracer channel
TELEMETRY_TRACE_DEFAULT = True
TELEMETRY_TRACE_CAPACITY = "trace_capacity"     # ring-buffer events
TELEMETRY_TRACE_CAPACITY_DEFAULT = 65536
TELEMETRY_METRICS_JSONL = "metrics_jsonl"       # step stream path; None = off
TELEMETRY_METRICS_JSONL_DEFAULT = None
TELEMETRY_METRICS_FSYNC = "metrics_fsync"       # fsync each step record
TELEMETRY_METRICS_FSYNC_DEFAULT = False
TELEMETRY_MFU = "mfu"                           # cost_analysis MFU channel
TELEMETRY_MFU_DEFAULT = True
# measured HBM accounting channel (runtime/memory_accounting.py): per-jit
# memory_analysis() + device watermark gauges; shares the lazy compile
# cache with the MFU channel when both are armed
TELEMETRY_MEMORY = "memory"
TELEMETRY_MEMORY_DEFAULT = True
# explicit bf16 peak TFLOPS per device for MFU/HFU ratios; 0 = auto from
# the device kind (unknown kinds — CPU meshes — report mfu=None)
TELEMETRY_PEAK_TFLOPS = "peak_tflops_per_device"
TELEMETRY_PEAK_TFLOPS_DEFAULT = 0.0

PIPELINE = "pipeline"               # pipeline engine knobs
PIPELINE_STAGES = "stages"
PIPELINE_STAGES_DEFAULT = 1
PIPELINE_PARTITION = "partition"
PIPELINE_PARTITION_DEFAULT = "parameters"
PIPELINE_SEED_LAYERS = "seed_layers"
PIPELINE_SEED_LAYERS_DEFAULT = False
PIPELINE_ACTIVATION_CHECKPOINT_INTERVAL = "activation_checkpoint_interval"
PIPELINE_ACTIVATION_CHECKPOINT_INTERVAL_DEFAULT = 0
PIPELINE_SCHEDULE = "schedule"          # "1f1b" | "interleaved" | "zb-h1"
PIPELINE_SCHEDULE_DEFAULT = "1f1b"
PIPELINE_VIRTUAL_STAGES = "virtual_stages"  # model chunks per stage (>=1)
PIPELINE_VIRTUAL_STAGES_DEFAULT = 1
# zb-h1 activation stashing: run the forward once per (chunk, micro) and
# stash its vjp residuals so dgrad/wgrad skip the forward recompute.
# "auto" arms it whenever the zb-h1 schedule is armed (and the budget
# fits); True insists (still DISARMS loudly on blockers); False keeps
# the remat-honest split backward.
PIPELINE_STASH = "activation_stashing"
PIPELINE_STASH_DEFAULT = "auto"
# peak stash bytes allowed PER STAGE (0 = unbounded). When the analytic
# peak (peak_live_stash x per-micro stash bytes) exceeds this on any
# stage, stashing DISARMS (falls back to remat) naming the stage.
PIPELINE_STASH_BUDGET = "stash_budget"
PIPELINE_STASH_BUDGET_DEFAULT = 0
