"""Analytic + measured HBM accounting — the third accounting leg.

``comm_accounting`` prices bytes on the wire and ``bubble_accounting``
replays time; this module prices the resource that actually gates both —
device memory.  Two sides, cross-checked:

- **Analytic**: a pure shape/dtype per-component byte model (params /
  gradient accumulators / optimizer state / fp16 masters per ZeRO stage,
  gathered stage-3 weights with fwd→bwd persistence, ZB stash residuals,
  the serving KV block pool, quantization scratch).  No device, no jax
  array is touched, so the numbers are deterministic on any host and
  ``tools/mem_budget.py`` can gate peak-bytes regressions in tier-1
  exactly like ``comm_budgets.json`` gates wire bytes.
- **Measured**: what the compiler actually reserved, read from
  ``compiled.memory_analysis()`` (argument/output/temp/alias bytes) per
  registered step jit, plus the runtime's ``device.memory_stats()`` HBM
  watermark where the backend reports one.  Registration is the
  telemetry capture-by-shape idiom (``register_by_shape``): the shape
  structs are taken at first dispatch, the ``lower().compile()`` runs
  lazily at report time, and the compiled object is SHARED with the MFU
  ledger (:class:`telemetry.mfu.MfuAccounting`) — arming both costs ONE
  compile per jit and zero compiles on the step path.

This module is also THE normalizer for the backend-dependent probe
shapes: ``memory_analysis()`` has been an attribute object, a dict and
None across jax versions/backends, and ``memory_stats()`` is a dict on
TPU/GPU, ``None`` on CPU, and raises on some plugin backends — the same
treatment ``telemetry.mfu.normalize_cost_analysis`` gives
``cost_analysis()``.  The ad-hoc readers in the flops profiler,
``runtime/utils.see_memory_usage`` and ``utils/timer.memory_usage`` all
delegate here.

Consumers: ``engine.memory_report()`` on all three engines (training,
pipeline, serving), the ``memory`` section of ``telemetry_report()``,
``tools/mem_budget.py`` + ``tools/memory_budgets.json``, and the
``_arm_stash`` / ``_arm_stage3`` analytic-vs-measured cross-checks.
"""
import threading
from typing import Optional, Sequence

import numpy as np

from deepspeed_tpu.runtime.comm_accounting import LeafSpec  # noqa: F401
from deepspeed_tpu.runtime.quantization import (DEFAULT_BLOCK_SIZE,
                                                block_layout)
from deepspeed_tpu.utils.logging import logger

# byte fields of xla_extension.CompiledMemoryStats (and its dict twins)
_MEM_FIELDS = ("argument", "output", "temp", "alias", "generated_code")

# the default analytic-vs-measured tolerance: an analytic estimate more
# than 15% under the compiler's own number is a sizing hazard (budgets
# derived from it under-provision) and is warned about loudly
UNDERESTIMATE_TOLERANCE = 0.15


# ---------------------------------------------------------------------------
# normalizers — THE one place the per-backend probe variants are handled
# ---------------------------------------------------------------------------

def normalize_memory_analysis(compiled_or_stats):
    """``compiled.memory_analysis()`` → plain byte dict, whatever shape
    the backend hands back.

    Accepts a compiled object (``memory_analysis()`` is called on it), a
    stats object (``*_size_in_bytes`` attributes), a dict (either
    ``*_size_in_bytes`` or ``*_bytes`` keys), or None.  Returns::

        {"argument_bytes", "output_bytes", "temp_bytes", "alias_bytes",
         "generated_code_bytes", "peak_bytes", "modeled"}

    ``peak_bytes`` prefers the backend's own peak when it reports one
    (``peak_memory_in_bytes``, TPU), else derives the standard XLA
    footprint ``argument + output - alias + temp``.  ``modeled=False``
    (all fields None) when the backend reports nothing — callers report
    the gap honestly instead of crashing on a quirk.
    """
    stats = compiled_or_stats
    if hasattr(stats, "memory_analysis"):
        try:
            stats = stats.memory_analysis()
        except (AttributeError, NotImplementedError, RuntimeError) as e:
            return dict(_EMPTY_ANALYSIS, error=str(e))
    if stats is None:
        return dict(_EMPTY_ANALYSIS)

    def read(field):
        if isinstance(stats, dict):
            v = stats.get(f"{field}_size_in_bytes",
                          stats.get(f"{field}_bytes"))
        else:
            v = getattr(stats, f"{field}_size_in_bytes", None)
        return int(v) if v is not None else None

    out = {f"{f}_bytes": read(f) for f in _MEM_FIELDS}
    peak = stats.get("peak_memory_in_bytes") if isinstance(stats, dict) \
        else getattr(stats, "peak_memory_in_bytes", None)
    if peak is None and None not in (out["argument_bytes"],
                                     out["output_bytes"],
                                     out["alias_bytes"], out["temp_bytes"]):
        peak = (out["argument_bytes"] + out["output_bytes"]
                - out["alias_bytes"] + out["temp_bytes"])
    out["peak_bytes"] = int(peak) if peak is not None else None
    out["modeled"] = any(v is not None for v in out.values())
    return out


_EMPTY_ANALYSIS = {f"{f}_bytes": None for f in _MEM_FIELDS}
_EMPTY_ANALYSIS.update({"peak_bytes": None, "modeled": False})


def normalize_memory_stats(device_or_stats):
    """``device.memory_stats()`` → ``{"bytes_in_use",
    "peak_bytes_in_use", "bytes_limit"}`` or None.

    Accepts a device object (``memory_stats()`` is called; per-backend
    errors are swallowed), a stats dict, or None.  Returns None when the
    backend reports nothing (the CPU backend) — "no watermark" is a
    reportable fact, not an exception.
    """
    stats = device_or_stats
    if hasattr(stats, "memory_stats"):
        try:
            stats = stats.memory_stats()
        except Exception:  # lint: allow-broad-except — plugin backends
            # raise assorted RuntimeErrors for unimplemented stats; a
            # memory probe must never take down the caller
            stats = None
    if not isinstance(stats, dict) or not stats:
        return None
    out = {}
    for key in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
        v = stats.get(key)
        out[key] = int(v) if v is not None else None
    return out


def device_memory_report(devices=None):
    """Per-device HBM snapshot: ``memory_stats`` watermark + headroom
    where the backend reports them, honest Nones where it doesn't.

    One entry per device: ``{"id", "kind", "platform", "bytes_in_use",
    "peak_bytes_in_use", "bytes_limit", "headroom_bytes"}``.  Cold-path
    builder — call it from reports, never from a step loop.
    """
    if devices is None:
        import jax

        devices = jax.local_devices()
    out = []
    for d in devices:
        stats = normalize_memory_stats(d) or {}
        entry = {
            "id": getattr(d, "id", None),
            "kind": getattr(d, "device_kind", None),
            "platform": getattr(d, "platform", None),
            "bytes_in_use": stats.get("bytes_in_use"),
            "peak_bytes_in_use": stats.get("peak_bytes_in_use"),
            "bytes_limit": stats.get("bytes_limit"),
        }
        if entry["bytes_limit"] and entry["bytes_in_use"] is not None:
            entry["headroom_bytes"] = \
                entry["bytes_limit"] - entry["bytes_in_use"]
        else:
            entry["headroom_bytes"] = None
        out.append(entry)
    return out


# ---------------------------------------------------------------------------
# analytic per-component model (pure shape math — no devices, no jax)
# ---------------------------------------------------------------------------

def bytes_of(shape: Sequence[int], dtype) -> int:
    """Bytes of one dense array of ``shape`` in ``dtype``."""
    n = 1
    for d in shape:
        n *= int(d)
    return n * np.dtype(dtype).itemsize


def leaf_device_bytes(leaf) -> int:
    """Per-device bytes of one CONCRETE jax array (or any shaped value):
    the leaf's shard shape under its sharding × itemsize — exact, not
    modeled, because the placement is known.  Host/numpy leaves count
    their full shape (they are replicated by construction)."""
    shape = tuple(np.shape(leaf))
    sharding = getattr(leaf, "sharding", None)
    if sharding is not None and hasattr(sharding, "shard_shape"):
        try:
            shape = tuple(sharding.shard_shape(shape))
        except (ValueError, TypeError):
            pass
    dt = getattr(leaf, "dtype", None)
    if dt is None:
        dt = np.asarray(leaf).dtype
    return bytes_of(shape, dt)


def tree_device_bytes(tree) -> int:
    """Per-device bytes of a pytree of concrete arrays (0 for None/empty
    subtrees)."""
    import jax

    return sum(leaf_device_bytes(l)
               for l in jax.tree_util.tree_leaves(tree))


def _partitioned(leaf: LeafSpec, dp: int) -> bool:
    return (dp > 1 and leaf.shard_dim is not None
            and leaf.shape[leaf.shard_dim] % dp == 0)


def _leaves_bytes(leaves: Sequence[LeafSpec], dp: int, elem_bytes: int,
                  sharded: bool) -> int:
    """Per-device bytes of a param-shaped component: partitioned leaves
    divide by dp when the component is ZeRO-``sharded``; indivisible
    leaves stay whole either way (mesh.zero_merge_spec semantics)."""
    total = 0
    for leaf in leaves:
        n = leaf.elements
        if sharded and _partitioned(leaf, dp):
            n //= dp
        total += n * elem_bytes
    return total


def quantization_scratch_bytes(leaves: Sequence[LeafSpec], dp: int,
                               block_size: int = DEFAULT_BLOCK_SIZE) -> int:
    """Transient scratch of one quantized collective in flight: the int8
    payload + fp32 per-block scales of the LARGEST leaf (collectives
    serialize on the wire, so one quantize buffer is live at a time).
    0 when nothing is partitioned."""
    worst = 0
    for leaf in leaves:
        if not _partitioned(leaf, dp):
            continue
        _, nb, npad = block_layout(leaf.elements, block_size)
        worst = max(worst, npad * 1 + nb * 4)
    return worst


def kv_pool_bytes(n_layer: int, num_blocks: int, n_head: int,
                  block_size: int, head_dim: int, *,
                  kv_dtype="bfloat16", quantized: bool = False,
                  shards: int = 1, shared_blocks: int = 0,
                  shared_refs: int = 1) -> int:
    """Per-shard device bytes of the serving paged KV pool: k + v of
    ``(L, num_blocks/shards, H, block_size, D)`` (int8 when quantized,
    else ``kv_dtype``) plus the two fp32 per-(token, head)-row scale
    tensors int8 storage carries.  THE builder both
    ``PagedKVPool.stats()`` and the serving ``memory_report()`` price
    the pool through — byte-exact against the allocated arrays.

    Under prefix sharing (ISSUE 17), ``num_blocks`` may be the LOGICAL
    block demand of the workload: ``shared_blocks`` distinct blocks each
    mapped read-only by ``shared_refs`` requests are stored ONCE, so the
    physical pool shrinks by ``shared_blocks * (shared_refs - 1)`` —
    refcounted shared storage is never priced per reference.  The
    defaults (no sharing) price exactly the allocated arrays."""
    assert shared_blocks >= 0 and shared_refs >= 1, \
        (shared_blocks, shared_refs)
    physical = num_blocks - shared_blocks * (shared_refs - 1)
    assert physical > 0, (num_blocks, shared_blocks, shared_refs)
    assert physical % shards == 0, (physical, shards)
    bps = physical // shards
    store = 1 if quantized else np.dtype(kv_dtype).itemsize
    kv = 2 * n_layer * bps * n_head * block_size * head_dim * store
    scales = 2 * n_layer * bps * n_head * block_size * 4 if quantized else 0
    return kv + scales


def sparse_kv_blocks_per_seq(n_positions: int, block_size: int, *,
                             num_sliding_window_blocks: int,
                             num_global_blocks: int = 1) -> int:
    """RESIDENT pool blocks one sequence of ``n_positions`` tokens holds
    under a sliding-window + global-anchor sparse attention policy
    (serving/sparse_context.py) with window-expired reclamation: the
    ``num_global_blocks`` anchors stay pinned and only the trailing
    ``num_sliding_window_blocks`` window stays mapped — everything
    between has been returned to the allocator.  This is the
    active-page factor long-context pool sizing composes into
    :func:`kv_pool_bytes`: ``num_blocks ~= slots *
    sparse_kv_blocks_per_seq(...) + shards`` instead of ``slots *
    ceil(n_positions / block_size) + shards``.  Short sequences that
    never outgrow the window are priced at their dense footprint."""
    assert num_sliding_window_blocks >= 1 and num_global_blocks >= 0
    dense = -(-int(n_positions) // int(block_size))
    return min(dense, num_global_blocks + num_sliding_window_blocks)


def train_memory_report(leaves: Sequence[LeafSpec], dp: int, *,
                        zero_stage: int = 0,
                        compute_dtype="float32",
                        mixed_precision: Optional[bool] = None,
                        optimizer_slots: int = 2,
                        cpu_offload: bool = False,
                        quantized_gradients: bool = False,
                        block_size: int = DEFAULT_BLOCK_SIZE,
                        gathered_stage3_bytes: int = 0,
                        stash_bytes: int = 0,
                        extra_transient_bytes: int = 0) -> dict:
    """Analytic per-device HBM bytes of one training configuration —
    pure shape/mesh math, the memory twin of
    ``comm_accounting.volume_report``.

    Components (bytes per device):

    - ``params``: compute dtype; ZeRO-sharded at rest under stage 3.
    - ``grad_accum``: fp32 accumulators; sharded under stage >= 2; ZERO
      under cpu_offload (grads stream to the host per micro).
    - ``master``: fp32 master copies under mixed precision (defaults to
      ``compute_dtype != float32``); sharded under stage >= 1; on the
      host under cpu_offload.
    - ``optimizer_state``: ``optimizer_slots`` fp32 param-shaped slots
      (Adam m+v = 2); sharded under stage >= 1; host under offload.
    - transients: ``gathered_stage3`` (scheduled stage-3 weights live
      fwd→bwd — ``GatherPlan.gathered_bytes``), ``stash`` (ZB residual
      peak), ``quantization_scratch`` (qgZ quantize buffer), plus any
      ``extra_transient_bytes`` the caller prices.

    ``peak_bytes = persistent + transient`` is the number
    ``tools/mem_budget.py`` budgets and the measured watermark is judged
    against.
    """
    if mixed_precision is None:
        mixed_precision = np.dtype(compute_dtype).itemsize < 4
    compute_b = np.dtype(compute_dtype).itemsize
    components = {
        "params_bytes": _leaves_bytes(leaves, dp, compute_b,
                                      sharded=zero_stage >= 3),
        "grad_accum_bytes": 0 if cpu_offload else _leaves_bytes(
            leaves, dp, 4, sharded=zero_stage >= 2),
        "master_bytes": 0 if (cpu_offload or not mixed_precision)
        else _leaves_bytes(leaves, dp, 4, sharded=zero_stage >= 1),
        "optimizer_state_bytes": 0 if cpu_offload else
        optimizer_slots * _leaves_bytes(leaves, dp, 4,
                                        sharded=zero_stage >= 1),
    }
    transient = {
        "gathered_stage3_bytes": int(gathered_stage3_bytes),
        "stash_bytes": int(stash_bytes),
        "quantization_scratch_bytes": quantization_scratch_bytes(
            leaves, dp, block_size) if quantized_gradients else 0,
        "extra_transient_bytes": int(extra_transient_bytes),
    }
    persistent = sum(components.values())
    transient_total = sum(transient.values())
    return {
        "config": {
            "dp": dp, "zero_stage": zero_stage,
            "compute_dtype": np.dtype(compute_dtype).name,
            "mixed_precision": bool(mixed_precision),
            "optimizer_slots": optimizer_slots,
            "cpu_offload": bool(cpu_offload),
        },
        "components": components,
        "transient": transient,
        "persistent_bytes": persistent,
        "transient_bytes": transient_total,
        "peak_bytes": persistent + transient_total,
    }


# ---------------------------------------------------------------------------
# measured side: per-jit memory_analysis registry (capture-by-shape)
# ---------------------------------------------------------------------------

def register_by_shape(mem, name, jit_fn, args, mesh=None,
                      calls_per_step=1.0, expect_label=None):
    """The telemetry capture-by-shape idiom for the memory ledger: take
    a ``jax.ShapeDtypeStruct`` tree of the REAL dispatch args NOW
    (donated buffers still alive), record the EXACT per-device argument
    bytes from their live shard shapes, and register a lazy
    ``lower().compile()`` closure that only runs at report time.  No-op
    when ``mem``/``jit_fn`` is None or ``name`` is already registered.

    When the engine also arms MFU, pass the shared
    :class:`~deepspeed_tpu.telemetry.mfu.MfuAccounting` to
    ``MemoryAccounting(shared=...)`` and register the same names with
    both — the compiled object is cached once between the two ledgers.

    ``expect_label`` arms the analytic-vs-measured cross-check for this
    jit: the analytic side is the trace-level output footprint
    (``jax.eval_shape`` over the same shape structs, resolved lazily at
    report time — no trace on the step path) plus one argument-sized
    working-set allowance, and the measured side is ``temp + output``
    from ``memory_analysis()``.  The claim being checked is the one
    budgets rely on: a step jit's transient needs are its outputs plus
    at most an input-sized scratch — when XLA's own number exceeds that
    by >15%, the warning says the hand model under-provisions.  Use it
    only for jits the engine sizes a budget from (the micro step, the
    stage-3 staged forward, the ZB stash forwards, the serving decode)
    — reduction jits whose outputs are scalars would warn spuriously.
    """
    if mem is None or jit_fn is None or mem.has(name):
        return
    import jax

    from deepspeed_tpu.telemetry.mfu import shape_structs

    structs = shape_structs(args)
    argument_bytes = sum(leaf_device_bytes(l)
                         for l in jax.tree_util.tree_leaves(args))

    def make_compiled():
        if mesh is None:
            return jit_fn.lower(*structs).compile()
        with jax.set_mesh(mesh):
            return jit_fn.lower(*structs).compile()

    mem.register(name, make_compiled, calls_per_step=calls_per_step,
                 argument_bytes=argument_bytes)
    if expect_label:
        def analytic_transient_bytes():
            if mesh is None:
                out = jax.eval_shape(jit_fn, *structs)
            else:
                with jax.set_mesh(mesh):
                    out = jax.eval_shape(jit_fn, *structs)
            # per-device where the abstract outputs carry a sharding
            # (leaf_device_bytes applies shard_shape); jax versions
            # whose eval_shape drops out-shardings fall back to global
            # shapes — a LOOSER bound there (the guard still catches
            # gross underestimates; the tight per-device exactness
            # check is argument_delta, which is always shard-exact)
            out_bytes = sum(leaf_device_bytes(l)
                            for l in jax.tree_util.tree_leaves(out))
            return out_bytes + argument_bytes

        mem.expect(name, expect_label, analytic_transient_bytes,
                   field="transient_bytes")


class MemoryAccounting:
    """Per-jit measured-memory registry + cross-check ledger.

    ``shared`` is the engine's :class:`telemetry.mfu.MfuAccounting`:
    when the same jit name is registered with both, the compiled object
    comes from the MFU cache — ONE ``lower().compile()`` serves both the
    FLOPs and the bytes ledger.  All reads are lazy (report time); the
    step path only ever pays the registration no-op check.
    """

    def __init__(self, shared=None):
        self._shared = shared
        self._jits = {}      # name -> (make_compiled, calls/step, arg B)
        self._compiled = {}  # own compile cache (used when not shared)
        self._measured = {}  # name -> normalized analysis (lazy)
        self._expect = {}    # name -> expectation dict
        self._checked = {}   # name -> cross-check verdict
        self._lock = threading.Lock()

    def has(self, name):
        return name in self._jits

    def register(self, name, make_compiled, calls_per_step=1.0,
                 argument_bytes=None):
        with self._lock:
            if name not in self._jits:
                self._jits[name] = (make_compiled, float(calls_per_step),
                                    argument_bytes)

    def expect(self, name, label, analytic_bytes,
               field="output_bytes", tolerance=UNDERESTIMATE_TOLERANCE):
        """Record an arming-time analytic claim about one jit —
        ``_arm_stash`` / ``_arm_stage3`` call this with the peak bytes
        their budget checks were sized from.  ``analytic_bytes`` may be
        a zero-arg callable resolved lazily at cross-check time (so
        arming never pays the abstract eval twice).  The cross-check
        compares it against the measured ``field`` and warns loudly on a
        > ``tolerance`` underestimate."""
        self._expect[name] = {"label": label, "analytic": analytic_bytes,
                              "field": field, "tolerance": float(tolerance)}

    def _get_compiled(self, name):
        shared = self._shared
        if shared is not None and shared.has(name):
            return shared.compiled(name)
        if name not in self._compiled:
            self._compiled[name] = self._jits[name][0]()
        return self._compiled[name]

    def measured_memory(self):
        """{name: normalized memory_analysis + calls_per_step +
        analytic argument bytes} — compiled lazily on first call, cached
        after; one program's lowering failure reports its error string
        instead of poisoning the rest (the MFU ``costs()`` contract)."""
        with self._lock:
            jits = dict(self._jits)
        for name, (_make, calls, arg_bytes) in jits.items():
            if name in self._measured:
                continue
            try:
                entry = normalize_memory_analysis(self._get_compiled(name))
            except Exception as e:  # lint: allow-broad-except — one
                # program's lowering quirk must not kill the report
                entry = dict(_EMPTY_ANALYSIS,
                             error=f"{type(e).__name__}: {e}")
            entry["calls_per_step"] = calls
            entry["analytic_argument_bytes"] = arg_bytes
            if arg_bytes and entry.get("argument_bytes"):
                entry["argument_delta"] = \
                    entry["argument_bytes"] / arg_bytes - 1.0
            else:
                entry["argument_delta"] = None
            # the working set beyond the (exactly-priced) arguments —
            # what the transient cross-checks compare against
            out_b, tmp_b = entry.get("output_bytes"), entry.get("temp_bytes")
            entry["transient_bytes"] = (out_b or 0) + (tmp_b or 0) \
                if (out_b is not None or tmp_b is not None) else None
            self._measured[name] = entry
        return dict(self._measured)

    def has_expectation(self, name):
        return name in self._expect

    def cross_check(self, warn=True):
        """Resolve every armed expectation against the measured side.

        Returns ``{name: {"label", "analytic_bytes", "measured_bytes",
        "ratio", "underestimated"}}``.  A measured value more than
        ``tolerance`` over the analytic claim means the hand-derived
        budget model under-provisions — warned per jit (once), in the
        DISARM-warning voice: the budget sized from that estimate should
        not be trusted until re-derived."""
        measured = self.measured_memory()
        for name, exp in self._expect.items():
            if name in self._checked:
                continue
            entry = measured.get(name)
            if entry is None or entry.get(exp["field"]) is None:
                continue        # not dispatched / backend silent: retry
            analytic = exp["analytic"]
            if callable(analytic):
                try:
                    analytic = analytic()
                except Exception as e:  # lint: allow-broad-except — the
                    # measured side's contract applies here too: one
                    # program's abstract-eval quirk (dead mesh after an
                    # elastic restart, backend tracing bug) must not
                    # kill the whole memory report
                    self._checked[name] = {
                        "label": exp["label"], "field": exp["field"],
                        "analytic_bytes": None, "measured_bytes":
                            entry[exp["field"]], "ratio": None,
                        "underestimated": False,
                        "error": f"{type(e).__name__}: {e}",
                    }
                    continue
            got = entry[exp["field"]]
            ratio = got / analytic if analytic else None
            under = bool(analytic) and got > analytic * (1 + exp["tolerance"])
            self._checked[name] = {
                "label": exp["label"], "field": exp["field"],
                "analytic_bytes": int(analytic) if analytic else analytic,
                "measured_bytes": got, "ratio": ratio,
                "underestimated": under,
            }
            if under and warn:
                logger.warning(
                    "memory accounting: analytic model UNDERESTIMATES the "
                    "compiler for %s (%s) — measured %s = %d B vs analytic "
                    "%d B (> %.0f%% over); treat budgets sized from this "
                    "estimate (stash_budget / stage3_prefetch_budget) as "
                    "DISARMED until the model is re-derived",
                    name, exp["label"], exp["field"], got, int(analytic),
                    100 * exp["tolerance"])
        return dict(self._checked)


# ---------------------------------------------------------------------------
# report builder (cold path — graftlint flags calls from hot step fns)
# ---------------------------------------------------------------------------

def memory_report(*, analytic=None, accounting=None, devices=None,
                  extra=None):
    """Assemble the unified memory report every engine surface uses:

    - ``analytic``: the caller's component model (engine state bytes or
      :func:`train_memory_report` output);
    - ``measured``: per-jit ``memory_analysis`` + analytic-vs-measured
      deltas + expectation cross-checks, when a
      :class:`MemoryAccounting` is armed;
    - ``devices``: per-device ``memory_stats`` watermark + headroom.

    Pure host work, but O(registered jits) with lazy compiles on first
    call — a cold report builder, never for the step path.
    """
    report = {
        "armed": accounting is not None,
        "analytic": analytic,
        "devices": device_memory_report(devices),
    }
    if accounting is not None:
        report["measured"] = accounting.measured_memory()
        report["cross_check"] = accounting.cross_check()
    if extra:
        report.update(extra)
    return report
