"""Checkpoint serialization helpers shared by the engines.

ml_dtypes arrays (bfloat16, float8_*) are not portable through np.savez
as-is: kind-'V' dtypes land as raw void that np.load returns uninterpreted,
and kind-'f' extension dtypes (float8_e5m2) write a descr like '<f1' that
np.load REJECTS ("not a valid dtype descriptor") — a checkpoint that can
never be read back.  So every non-builtin dtype is stored as a void view of
its bytes with the dtype name recorded alongside, and re-viewed through
ml_dtypes on load (bit-exact round trip)."""
import numpy as np


def _storable(arr):
    """View non-builtin (ml_dtypes) arrays as void bytes so np.load can
    always parse the saved descr."""
    if arr.dtype.isbuiltin != 1:
        return arr.view(np.dtype(f"V{arr.dtype.itemsize}"))
    return arr


def leaves_to_npz_dict(flat_leaves):
    """Host/device leaves -> kwargs for np.savez (leaf_i + dtype_i pairs)."""
    out = {}
    for i, leaf in enumerate(flat_leaves):
        arr = np.asarray(leaf)
        out[f"leaf_{i}"] = _storable(arr)
        out[f"dtype_{i}"] = np.str_(str(arr.dtype))
    return out


def npz_dict_to_leaves(data):
    """Inverse of leaves_to_npz_dict; returns the list of numpy leaves."""
    n = sum(1 for name in data.files if name.startswith("leaf_"))
    leaves = []
    for i in range(n):
        arr = data[f"leaf_{i}"]
        if arr.dtype.kind == "V" and f"dtype_{i}" in data.files:
            import ml_dtypes

            arr = arr.view(np.dtype(getattr(ml_dtypes, str(data[f"dtype_{i}"]))))
        leaves.append(arr)
    return leaves


def named_leaf_entry(name, leaf):
    """One name-keyed npz entry (+ dtype sidecar for ml_dtypes payloads)."""
    arr = np.asarray(leaf)
    return {name: _storable(arr), f"dtype::{name}": np.str_(str(arr.dtype))}


def named_leaf_lookup(data, name):
    """Inverse of named_leaf_entry against an open np.load handle."""
    arr = data[name]
    dkey = f"dtype::{name}"
    if arr.dtype.kind == "V" and dkey in data.files:
        import ml_dtypes

        arr = arr.view(np.dtype(getattr(ml_dtypes, str(data[dkey]))))
    return arr
