"""Analytic communication-volume accounting for the ZeRO paths.

Computes, per optimizer step and per collective, the exact bytes each
configuration moves — from shapes, dtypes and the mesh alone.  No device is
touched, so the numbers are deterministic on CPU and the comm wins of the
quantized collectives (qgZ/qwZ, ZeRO++ arxiv 2306.10209) are assertable in
tier-1 tests without TPU hardware.

Per-device wire bytes use the standard ring / bidirectional decompositions
XLA lowers dense collectives to (w = participating axis size, n elements,
s bytes/element):

    all-reduce       2 (w-1)/w * n * s      (reduce-scatter + all-gather)
    reduce-scatter     (w-1)/w * n * s
    all-gather         (w-1)/w * n * s
    all-to-all         (w-1)/w * n * s      (every rank keeps its own chunk)

Quantized collectives move int8 payloads plus fp32 per-block scales; the
padding/block layout matches quantization.block_layout exactly, so the
accounting is byte-accurate against what the quantizers put on the wire.

Consumers: DeepSpeedEngine.comm_volume_report() (per-engine, from the real
state shapes and shardings), the flops profiler's comm section, and
tools/comm_budget.py (regression guard over canonical configs).
"""
from dataclasses import asdict, dataclass
from typing import List, Optional, Sequence, Tuple

from deepspeed_tpu.runtime.quantization import (DEFAULT_BLOCK_SIZE,
                                                block_layout,
                                                sign_pack_layout)

DTYPE_BYTES = {
    "float64": 8, "float32": 4, "float16": 2, "bfloat16": 2,
    "int64": 8, "int32": 4, "int16": 2, "int8": 1, "uint8": 1, "bool": 1,
}


def dtype_bytes(dtype) -> int:
    name = getattr(dtype, "name", None) or str(dtype)
    if name not in DTYPE_BYTES:
        raise KeyError(f"unknown dtype {name!r} for comm accounting")
    return DTYPE_BYTES[name]


@dataclass
class Collective:
    """One logical collective: ``bytes_per_device`` is the wire traffic each
    participating device SENDS per invocation; ``count_per_step`` scales it
    to one optimizer step (e.g. gradient-accumulation micro-steps)."""
    name: str            # e.g. "grad_rs:params/w1"
    op: str              # all-reduce | reduce-scatter | all-gather | all-to-all
    dtype: str
    elements: int        # logical elements moved (pre-ring-factor)
    axis_size: int
    bytes_per_device: int
    count_per_step: int = 1
    link: str = "flat"   # flat | intra | inter (hierarchical qgZ hops)

    @property
    def bytes_per_step(self) -> int:
        return self.bytes_per_device * self.count_per_step


def _ring(w: int) -> float:
    return (w - 1) / w if w > 1 else 0.0


def allreduce_bytes(n: int, elem_bytes: int, w: int) -> int:
    return int(round(2 * _ring(w) * n * elem_bytes))


def reduce_scatter_bytes(n: int, elem_bytes: int, w: int) -> int:
    return int(round(_ring(w) * n * elem_bytes))


def all_gather_bytes(n: int, elem_bytes: int, w: int) -> int:
    return int(round(_ring(w) * n * elem_bytes))


def all_to_all_bytes(n: int, elem_bytes: int, w: int) -> int:
    return int(round(_ring(w) * n * elem_bytes))


@dataclass
class LeafSpec:
    """Shape/sharding facts the accounting needs about one gradient/param
    leaf.  ``shard_dim`` is the dimension the ZeRO spec shards over 'data'
    (None = leaf stays replicated and its gradient all-reduces densely)."""
    name: str
    shape: Tuple[int, ...]
    shard_dim: Optional[int]

    @property
    def elements(self) -> int:
        n = 1
        for d in self.shape:
            n *= int(d)
        return n


def _qgz_wire(n_rows: int, row_len: int, block_size: int, w: int):
    """(int8_bytes, scale_bytes) one rank sends for an all_to_all of
    ``n_rows`` independently-quantized rows of ``row_len`` elements over a
    group of size ``w`` — mirrors quantization.quantize_rows exactly."""
    _, nb, npad = block_layout(row_len, block_size)
    return (all_to_all_bytes(n_rows * npad, 1, w),
            all_to_all_bytes(n_rows * nb, 4, w))


def grad_exchange_collectives(
        leaves: Sequence[LeafSpec], dp: int, *,
        quantized: bool = False,
        block_size: int = DEFAULT_BLOCK_SIZE,
        intra_size: int = 0,
        grad_dtype: str = "float32",
        count_per_step: int = 1) -> List[Collective]:
    """Per-leaf collectives of one gradient exchange (one micro-step).

    Dense (the stage-2 baseline): shardable leaves reduce-scatter in
    ``grad_dtype`` (the fp32 accumulator dtype); unshardable leaves
    all-reduce.  Quantized (qgZ): shardable leaves move int8 + fp32 scales
    through one flat all_to_all, or two hierarchical hops when
    1 < intra_size < dp divides dp (the inter hop carries 1/intra_size of
    the data, re-quantized).
    """
    es = DTYPE_BYTES[grad_dtype]
    out: List[Collective] = []
    k = int(intra_size or 0)
    hier = quantized and 1 < k < dp and dp % k == 0
    for leaf in leaves:
        n = leaf.elements
        if leaf.shard_dim is None or dp <= 1:
            out.append(Collective(
                name=f"grad_ar:{leaf.name}", op="all-reduce",
                dtype=grad_dtype, elements=n, axis_size=dp,
                bytes_per_device=allreduce_bytes(n, es, dp),
                count_per_step=count_per_step))
            continue
        if not quantized:
            out.append(Collective(
                name=f"grad_rs:{leaf.name}", op="reduce-scatter",
                dtype=grad_dtype, elements=n, axis_size=dp,
                bytes_per_device=reduce_scatter_bytes(n, es, dp),
                count_per_step=count_per_step))
            continue
        if not hier:
            nloc = n // dp
            qb, sb = _qgz_wire(dp, nloc, block_size, dp)
            out.append(Collective(
                name=f"qgz_a2a:{leaf.name}", op="all-to-all", dtype="int8",
                elements=n, axis_size=dp, bytes_per_device=qb,
                count_per_step=count_per_step))
            out.append(Collective(
                name=f"qgz_scales:{leaf.name}", op="all-to-all",
                dtype="float32", elements=n, axis_size=dp,
                bytes_per_device=sb, count_per_step=count_per_step))
            continue
        m = dp // k
        nloc = n // dp
        # hop 1 (intra): k rows of m*nloc elements over groups of k
        qb1, sb1 = _qgz_wire(k, m * nloc, block_size, k)
        # hop 2 (inter): m rows of nloc elements over groups of m
        qb2, sb2 = _qgz_wire(m, nloc, block_size, m)
        out += [
            Collective(name=f"qgz_a2a_intra:{leaf.name}", op="all-to-all",
                       dtype="int8", elements=n, axis_size=k,
                       bytes_per_device=qb1, count_per_step=count_per_step,
                       link="intra"),
            Collective(name=f"qgz_scales_intra:{leaf.name}", op="all-to-all",
                       dtype="float32", elements=n, axis_size=k,
                       bytes_per_device=sb1, count_per_step=count_per_step,
                       link="intra"),
            Collective(name=f"qgz_a2a_inter:{leaf.name}", op="all-to-all",
                       dtype="int8", elements=n // k, axis_size=m,
                       bytes_per_device=qb2, count_per_step=count_per_step,
                       link="inter"),
            Collective(name=f"qgz_scales_inter:{leaf.name}", op="all-to-all",
                       dtype="float32", elements=n // k, axis_size=m,
                       bytes_per_device=sb2, count_per_step=count_per_step,
                       link="inter"),
        ]
    return out


def _row_wire(n_rows: int, row_len: int, block_size: int, bits: int):
    """(payload_bytes, scale_elems) one rank PUTS INTO a collective for
    ``n_rows`` independently-quantized rows of ``row_len`` elements —
    pre-ring-factor.  bits=1 mirrors quantization.quantize_signs_rows
    (packed sign bytes, sign_pack_layout); bits=8 mirrors quantize_rows
    (one int8 byte per padded element, block_layout).  Shared by the
    0/1 Adam wire model below so the accounting can never drift from
    what the kernel packs."""
    if bits == 1:
        _, nb, _, nbytes = sign_pack_layout(row_len, block_size)
        return n_rows * nbytes, n_rows * nb
    _, nb, npad = block_layout(row_len, block_size)
    return n_rows * npad, n_rows * nb


def zeroone_grad_exchange_collectives(
        leaves: Sequence[LeafSpec], dp: int, *,
        bits: int = 1,
        block_size: int = DEFAULT_BLOCK_SIZE,
        intra_size: int = 0,
        count_per_step: int = 1) -> List[Collective]:
    """Per-leaf collectives of ONE SYNCED ROUND of the 0/1 Adam wire
    (custom_collectives.quantized_all_reduce): quantize -> all_to_all
    reduce-scatter -> server requantize -> all-gather, every payload a
    packed sub-byte (or int8) code plus fp32 per-block scales.  Every
    leaf rides the wire regardless of shard_dim — params stay replicated
    (stage 0) and the optimizer flattens + pads each leaf to a multiple
    of dp, exactly as the kernel does.  Local rounds move ZERO bytes and
    have no collectives to price (test_hlo_contracts pins the compiled
    program to that)."""
    wire_dtype = "uint8" if bits == 1 else "int8"
    out: List[Collective] = []
    k = int(intra_size or 0)
    hier = 1 < k < dp and dp % k == 0
    for leaf in leaves:
        n = leaf.elements
        if dp <= 1:
            continue                     # quantize/dequantize twin: no wire
        nloc = (n + (-n) % dp) // dp     # optimizer pads flat leaf to dp
        if not hier:
            # worker RS: dp rows of nloc each through one all_to_all
            qb, sb = _row_wire(dp, nloc, block_size, bits)
            # server AG: the requantized own-chunk row, gathered over dp
            qg, sg = _row_wire(1, nloc, block_size, bits)
            out += [
                Collective(name=f"zeroone_a2a:{leaf.name}", op="all-to-all",
                           dtype=wire_dtype, elements=n, axis_size=dp,
                           bytes_per_device=all_to_all_bytes(qb, 1, dp),
                           count_per_step=count_per_step),
                Collective(name=f"zeroone_scales:{leaf.name}",
                           op="all-to-all", dtype="float32", elements=n,
                           axis_size=dp,
                           bytes_per_device=all_to_all_bytes(sb, 4, dp),
                           count_per_step=count_per_step),
                Collective(name=f"zeroone_ag:{leaf.name}", op="all-gather",
                           dtype=wire_dtype, elements=n, axis_size=dp,
                           bytes_per_device=all_gather_bytes(dp * qg, 1, dp),
                           count_per_step=count_per_step),
                Collective(name=f"zeroone_ag_scales:{leaf.name}",
                           op="all-gather", dtype="float32", elements=n,
                           axis_size=dp,
                           bytes_per_device=all_gather_bytes(dp * sg, 4, dp),
                           count_per_step=count_per_step),
            ]
            continue
        m = dp // k
        # RS hop 1 (intra): k rows of m*nloc over groups of k
        qb1, sb1 = _row_wire(k, m * nloc, block_size, bits)
        # RS hop 2 (inter): partial sums requantized, m rows of nloc over m
        qb2, sb2 = _row_wire(m, nloc, block_size, bits)
        # AG hop A (inter): own requantized chunk over groups of m ...
        qg, sg = _row_wire(1, nloc, block_size, bits)
        # ... AG hop B (intra): the hop-A buffers (m chunks) over groups of
        # k — the same code moves twice, never re-encoded
        out += [
            Collective(name=f"zeroone_a2a_intra:{leaf.name}",
                       op="all-to-all", dtype=wire_dtype, elements=n,
                       axis_size=k,
                       bytes_per_device=all_to_all_bytes(qb1, 1, k),
                       count_per_step=count_per_step, link="intra"),
            Collective(name=f"zeroone_scales_intra:{leaf.name}",
                       op="all-to-all", dtype="float32", elements=n,
                       axis_size=k,
                       bytes_per_device=all_to_all_bytes(sb1, 4, k),
                       count_per_step=count_per_step, link="intra"),
            Collective(name=f"zeroone_a2a_inter:{leaf.name}",
                       op="all-to-all", dtype=wire_dtype, elements=n // k,
                       axis_size=m,
                       bytes_per_device=all_to_all_bytes(qb2, 1, m),
                       count_per_step=count_per_step, link="inter"),
            Collective(name=f"zeroone_scales_inter:{leaf.name}",
                       op="all-to-all", dtype="float32", elements=n // k,
                       axis_size=m,
                       bytes_per_device=all_to_all_bytes(sb2, 4, m),
                       count_per_step=count_per_step, link="inter"),
            Collective(name=f"zeroone_ag_inter:{leaf.name}",
                       op="all-gather", dtype=wire_dtype, elements=n // k,
                       axis_size=m,
                       bytes_per_device=all_gather_bytes(m * qg, 1, m),
                       count_per_step=count_per_step, link="inter"),
            Collective(name=f"zeroone_ag_scales_inter:{leaf.name}",
                       op="all-gather", dtype="float32", elements=n // k,
                       axis_size=m,
                       bytes_per_device=all_gather_bytes(m * sg, 4, m),
                       count_per_step=count_per_step, link="inter"),
            Collective(name=f"zeroone_ag_intra:{leaf.name}",
                       op="all-gather", dtype=wire_dtype, elements=n,
                       axis_size=k,
                       bytes_per_device=all_gather_bytes(k * m * qg, 1, k),
                       count_per_step=count_per_step, link="intra"),
            Collective(name=f"zeroone_ag_scales_intra:{leaf.name}",
                       op="all-gather", dtype="float32", elements=n,
                       axis_size=k,
                       bytes_per_device=all_gather_bytes(k * m * sg, 4, k),
                       count_per_step=count_per_step, link="intra"),
        ]
    return out


def zeroone_volume_report(leaves: Sequence[LeafSpec], dp: int, *,
                          bits: int = 1,
                          block_size: int = DEFAULT_BLOCK_SIZE,
                          intra_size: int = 0,
                          local_steps_k: int = 1,
                          gas: int = 1) -> dict:
    """Per-step report for the 0/1 Adam optimizer wire, with the two
    yardsticks the acceptance bound is judged against alongside: the flat
    qgZ int8 gradient wire and the dense fp32 all-reduce.

    ``local_steps_k`` is the round length: one synced round (the only
    step that touches the wire) stands in for k optimizer steps, so the
    honest per-step figure is ``sync_round_bytes / k`` — the skipped
    local rounds are amortization, not free lunch, and both numbers are
    reported.  The yardsticks price the OTHER paths' conventions (qgZ
    exchanges per micro-step, hence x gas; the wire path syncs once per
    optimizer step regardless of gas — the fused step accumulates micro
    gradients device-locally)."""
    k_round = max(1, int(local_steps_k))
    sync = zeroone_grad_exchange_collectives(
        leaves, dp, bits=bits, block_size=block_size, intra_size=intra_size)
    sync_bytes = sum(c.bytes_per_step for c in sync)
    amortized = sync_bytes // k_round + (sync_bytes % k_round > 0)
    qgz_leaves = [LeafSpec(name=l.name, shape=l.shape,
                           shard_dim=zero_shard_dim(l.shape, dp))
                  for l in leaves]
    qgz = grad_exchange_collectives(qgz_leaves, dp, quantized=True,
                                    block_size=block_size,
                                    count_per_step=gas)
    qgz_bytes = sum(c.bytes_per_step for c in qgz)
    dense = grad_exchange_collectives(leaves, dp, quantized=False,
                                      count_per_step=1)
    dense_bytes = sum(c.bytes_per_step for c in dense)
    return {
        "config": {
            "dp": dp, "gas": gas, "bits": int(bits),
            "quantization_block_size": int(block_size),
            "hierarchical_intra_size": int(intra_size or 0),
            "local_steps_k": k_round,
        },
        "collectives": [asdict(c) | {"bytes_per_step": c.bytes_per_step}
                        for c in sync],
        "sync_round_bytes": sync_bytes,
        "local_round_bytes": 0,
        "amortized_grad_exchange_bytes_per_step": int(amortized),
        "warmup_grad_exchange_bytes_per_step": dense_bytes,
        "baseline": {
            "qgz_int8_wire_bytes_per_step": qgz_bytes,
            "fp32_allreduce_bytes_per_step": dense_bytes,
        },
        "vs_qgz_ratio": (amortized / qgz_bytes) if qgz_bytes else None,
        "vs_fp32_ratio": (amortized / dense_bytes) if dense_bytes else None,
    }


def param_gather_collectives(
        leaves: Sequence[LeafSpec], dp: int, *,
        quantized: bool = False,
        block_size: int = DEFAULT_BLOCK_SIZE,
        param_dtype: str = "bfloat16",
        count_per_step: int = 1) -> List[Collective]:
    """Collectives of the per-step parameter materialization: the all-gather
    of (ZeRO-sharded) weights back to the replicated compute layout.
    Dense: one all-gather in the compute dtype per shardable leaf.
    Quantized (qwZ / scheduled stage-3): all-gather int8 blocks + fp32
    scales instead.  ``count_per_step`` scales to one optimizer step: the
    stage-1/2 post-step materialization gathers once, the scheduled
    stage-3 path gathers once per MICRO-step (gas), and the implicit
    stage-3 path under a remat'd backward fetches every weight TWICE per
    micro (forward + backward recompute) — 2*gas."""
    es = DTYPE_BYTES[param_dtype]
    out: List[Collective] = []
    for leaf in leaves:
        if leaf.shard_dim is None or dp <= 1:
            continue                     # replicated leaf: nothing to gather
        n = leaf.elements
        if not quantized:
            out.append(Collective(
                name=f"param_ag:{leaf.name}", op="all-gather",
                dtype=param_dtype, elements=n, axis_size=dp,
                bytes_per_device=all_gather_bytes(n, es, dp),
                count_per_step=count_per_step))
            continue
        _, nb, npad = block_layout(n // dp, block_size)
        out += [
            Collective(name=f"qwz_ag:{leaf.name}", op="all-gather",
                       dtype="int8", elements=dp * npad, axis_size=dp,
                       bytes_per_device=all_gather_bytes(dp * npad, 1, dp),
                       count_per_step=count_per_step),
            Collective(name=f"qwz_scales:{leaf.name}", op="all-gather",
                       dtype="float32", elements=dp * nb, axis_size=dp,
                       bytes_per_device=all_gather_bytes(dp * nb, 4, dp),
                       count_per_step=count_per_step),
        ]
    return out


def volume_report(leaves: Sequence[LeafSpec], dp: int, *,
                  gas: int = 1,
                  quantized_gradients: bool = False,
                  quantized_weights: bool = False,
                  quantized_weights_mask: Optional[Sequence[bool]] = None,
                  block_size: int = DEFAULT_BLOCK_SIZE,
                  intra_size: int = 0,
                  param_dtype: str = "bfloat16",
                  gather_params: bool = True,
                  param_gathers_per_step: int = 1,
                  implicit_param_gathers_per_step: Optional[int] = None
                  ) -> dict:
    """Full per-step report for one configuration, with the dense-fp32
    baseline alongside so byte reductions are assertable directly.

    ``quantized_weights_mask``: per-leaf qwZ eligibility (the engine's
    offload push keeps TP-mixed/non-divisible leaves dense); None means
    ``quantized_weights`` applies to every shardable leaf.

    ``param_gathers_per_step``: how often the ACTIVE config materializes
    its partitioned weights per optimizer step (1 for the stage-1/2
    post-step gather, gas for the scheduled stage-3 per-micro gather,
    2*gas for implicit stage-3 under a remat'd backward — the forward
    gather plus the recompute refetch).  ``implicit_param_gathers_per_
    step``: when set, the baseline additionally prices the implicit
    XLA-scheduled stage-3 path (dense gathers at that count) as
    ``implicit_param_gather_bytes_per_step`` — the honest yardstick the
    scheduled path's acceptance bound is judged against."""
    grads = grad_exchange_collectives(
        leaves, dp, quantized=quantized_gradients, block_size=block_size,
        intra_size=intra_size, count_per_step=gas)
    if not gather_params:
        params = []
    elif quantized_weights and quantized_weights_mask is not None:
        dense_leaves = [l for l, q in zip(leaves, quantized_weights_mask)
                        if not q]
        q_leaves = [l for l, q in zip(leaves, quantized_weights_mask) if q]
        params = param_gather_collectives(
            dense_leaves, dp, quantized=False, param_dtype=param_dtype,
            count_per_step=param_gathers_per_step)
        params += param_gather_collectives(
            q_leaves, dp, quantized=True, block_size=block_size,
            param_dtype=param_dtype, count_per_step=param_gathers_per_step)
    else:
        params = param_gather_collectives(
            leaves, dp, quantized=quantized_weights,
            block_size=block_size, param_dtype=param_dtype,
            count_per_step=param_gathers_per_step)
    base = grad_exchange_collectives(leaves, dp, quantized=False,
                                     count_per_step=gas)
    base_rs = sum(c.bytes_per_step for c in base if c.op == "reduce-scatter")
    base_params = param_gather_collectives(
        leaves, dp, quantized=False, param_dtype=param_dtype) \
        if gather_params else []
    grad_bytes = sum(c.bytes_per_step for c in grads)
    param_bytes = sum(c.bytes_per_step for c in params)
    param_q_bytes = sum(c.bytes_per_step for c in params
                        if c.name.startswith(("qwz_ag", "qwz_scales")))
    report = {
        "config": {
            "dp": dp, "gas": gas,
            "quantized_gradients": bool(quantized_gradients),
            "quantized_weights": bool(quantized_weights),
            "quantization_block_size": int(block_size),
            "hierarchical_intra_size": int(intra_size or 0),
            "param_dtype": param_dtype,
            "param_gathers_per_step": int(param_gathers_per_step),
        },
        "collectives": [asdict(c) | {"bytes_per_step": c.bytes_per_step}
                        for c in grads + params],
        "grad_exchange_bytes_per_step": grad_bytes,
        "param_gather_bytes_per_step": param_bytes,
        "param_gather_quantized_bytes_per_step": param_q_bytes,
        "param_gather_dense_bytes_per_step": param_bytes - param_q_bytes,
        "total_bytes_per_step": grad_bytes + param_bytes,
        "inter_bytes_per_step": sum(c.bytes_per_step
                                    for c in grads + params
                                    if c.link == "inter"),
        "baseline": {
            "fp32_grad_exchange_bytes_per_step":
                sum(c.bytes_per_step for c in base),
            "fp32_reduce_scatter_bytes_per_step": base_rs,
            "dense_param_gather_bytes_per_step":
                sum(c.bytes_per_step for c in base_params),
        },
    }
    if implicit_param_gathers_per_step is not None:
        report["baseline"]["implicit_param_gather_bytes_per_step"] = \
            sum(c.bytes_per_step for c in base_params) \
            * int(implicit_param_gathers_per_step)
    baseline_total = report["baseline"]["fp32_grad_exchange_bytes_per_step"]
    report["grad_reduction_vs_fp32"] = (
        baseline_total / grad_bytes if grad_bytes else None)
    return report


def pipe_p2p_collectives(
        boundary_elems: int, micro_batches: int, *, stages: int,
        virtual_stages: int = 1,
        act_dtype: str = "float32",
        grad_dtype: Optional[str] = None,
        name: str = "pipe") -> List[Collective]:
    """Pipeline p2p traffic of one optimizer step as budgeted collectives.

    Each of the ``stages*virtual_stages - 1`` chunk boundaries moves one
    activation (forward) and one gradient (backward) of ``boundary_elems``
    elements per micro-batch; a p2p hop is a point-to-point copy, so the
    sender puts the FULL payload on the wire (no ring discount). One
    Collective per boundary per direction, honoring the dataclass
    contract: ``bytes_per_device`` is what the single sending stage puts
    on that edge per micro. Interleaved virtual stages multiply
    boundaries from (S-1) to (S*v - 1): the analytic bubble win
    (bubble_accounting) costs (v-1)*S extra boundary crossings per
    micro — this function is what makes that trade show up in
    comm_budgets.json instead of hiding in the schedule."""
    chunks = stages * virtual_stages
    grad_dtype = grad_dtype or act_dtype
    ea, eg = DTYPE_BYTES[act_dtype], DTYPE_BYTES[grad_dtype]
    out: List[Collective] = []
    for edge in range(max(0, chunks - 1)):
        out.append(Collective(
            name=f"p2p_act:{name}:e{edge}", op="p2p", dtype=act_dtype,
            elements=boundary_elems, axis_size=2,
            bytes_per_device=boundary_elems * ea,
            count_per_step=micro_batches))
        out.append(Collective(
            name=f"p2p_grad:{name}:e{edge}", op="p2p", dtype=grad_dtype,
            elements=boundary_elems, axis_size=2,
            bytes_per_device=boundary_elems * eg,
            count_per_step=micro_batches))
    return out


def pipe_p2p_bytes(act_bytes_per_edge: Sequence[int],
                   grad_bytes_per_edge: Sequence[int],
                   micro_batches: int) -> int:
    """Total p2p bytes per optimizer step from recorded per-boundary
    payload sizes. Heterogeneous BOUNDARIES (e.g. a chunk that changes
    width) are summed exactly; micro-batches are assumed shape-uniform
    (the engine slices one batch into equal micros — a data_iter yielding
    ragged micro shapes retraces jits anyway, and then this number is
    representative, to be cross-checked against the engine's measured
    bytes in pipeline_report()['p2p'])."""
    per_micro = sum(int(b) for b in act_bytes_per_edge) \
        + sum(int(b) for b in grad_bytes_per_edge)
    return per_micro * int(micro_batches)


def serving_decode_collectives(
        n_layer: int, n_embd: int, vocab_size: int, batch: int, *,
        tp: int = 1, act_dtype: str = "float32") -> List[Collective]:
    """Collectives of ONE continuous-batching decode step
    (deepspeed_tpu/serving/engine.py), per placement.

    **Batch-axis sharding** (the serving engine's shard_map layout,
    ``tp == 1``): slots, page tables, token/position vectors and the KV
    block pool are all split on the same mesh axis with params
    replicated.  Under the placement-semantics analysis of PAPERS.md
    (arXiv 2601.02311) every operator in the decode program carries the
    slot axis as a free (uniform) dimension — no operator contracts over
    it — so the induced resharding set is EMPTY: the step moves zero
    collective bytes, and tests/unit/test_hlo_contracts.py pins the
    compiled program to exactly that.  Returns [].

    **Tensor (model-axis) sharding** (``tp > 1``, the classic
    DeepSpeed-Inference kernel-injection layout): qkv/attn-out and
    mlp-in/mlp-out GEMM pairs are column/row split, so each layer
    all-reduces its (batch, 1, n_embd) activation twice per token, plus
    one all-reduce of the (batch, vocab) logits — the per-token latency
    tax batch sharding avoids, priced here for comm_budgets.json."""
    if tp <= 1:
        return []
    es = DTYPE_BYTES[act_dtype]
    out: List[Collective] = []
    act = batch * n_embd
    for layer in range(n_layer):
        for which in ("attn_out", "mlp_out"):
            out.append(Collective(
                name=f"decode_ar:{which}:l{layer}", op="all-reduce",
                dtype=act_dtype, elements=act, axis_size=tp,
                bytes_per_device=allreduce_bytes(act, es, tp)))
    n_logits = batch * vocab_size
    out.append(Collective(
        name="decode_ar:logits", op="all-reduce", dtype="float32",
        elements=n_logits, axis_size=tp,
        bytes_per_device=allreduce_bytes(n_logits, 4, tp)))
    return out


def serving_kv_handoff_collectives(
        n_layer: int, n_head: int, head_dim: int, *, blocks: int,
        block_size: int, kv_dtype: str = "float32",
        quantized: bool = False,
        name: str = "kv_handoff") -> List[Collective]:
    """Price ONE paged-block KV handoff between serving replicas — the
    disaggregated prefill/decode transfer of PAPERS.md 2601.02311.

    Prefill is compute-bound and bursty, decode is memory-bound and
    steady, so a fleet provisions them separately; the cost of the
    split is moving a finished prompt's KV ONCE from the prefill
    replica's pool to a decode replica's.  The payload is exactly the
    request's allocated blocks in the pool layout that already
    round-trips through checkpoints — ``blocks`` blocks of
    ``(n_layer, n_head, block_size, head_dim)`` rows for K and V each
    (the fixed-width page-table padding is an implementation detail of
    the fixed-shape gather, not wire payload).  int8 pools move int8
    payloads plus the per-(token, head) f32 scale rows, matching
    ``kv_cache``'s quantized layout byte-for-byte.

    A handoff is a point-to-point copy (the pipe-p2p convention): the
    sender puts the FULL payload on the wire, no ring discount.  The
    alternative this prices against is RE-PREFILLING prompt+generated
    at the destination — zero wire bytes but one full prefill of
    compute; ``serving/fleet.py`` reports both so the trade is visible
    per workload."""
    rows = n_layer * blocks * n_head * block_size
    elems = rows * head_dim
    dtype = "int8" if quantized else kv_dtype
    es = DTYPE_BYTES[dtype]
    out = [Collective(
        name=f"p2p_kv:{name}", op="p2p", dtype=dtype,
        elements=2 * elems, axis_size=2,
        bytes_per_device=2 * elems * es)]
    if quantized:
        out.append(Collective(
            name=f"p2p_kv_scales:{name}", op="p2p", dtype="float32",
            elements=2 * rows, axis_size=2,
            bytes_per_device=2 * rows * 4))
    return out


def serving_kv_handoff_bytes(n_layer: int, n_head: int, head_dim: int, *,
                             blocks: int, block_size: int,
                             kv_dtype: str = "float32",
                             quantized: bool = False) -> int:
    """Total wire bytes of one KV handoff (sum over its collectives)."""
    return sum(c.bytes_per_device for c in serving_kv_handoff_collectives(
        n_layer, n_head, head_dim, blocks=blocks, block_size=block_size,
        kv_dtype=kv_dtype, quantized=quantized))


def serving_gather_bytes_per_step(
        n_layer: int, n_head: int, block_size: int, head_dim: int, *,
        pages: int, batch: int = 1, kv_dtype: str = "float32",
        quantized: bool = False) -> int:
    """HBM bytes ONE decode step's KV gather reads: K and V of
    ``pages`` pool pages per lane, per layer — the memory-bound side of
    decode, and where the sparse page policy's active-page factor lands
    (``pages`` is the page-table width W dense, the policy's fixed K
    sparse — the serve_bench A/B's ≥4x claim IS this ratio).  int8
    pools read int8 rows plus the per-(token, head) f32 scales, the
    same layout ``kv_cache._pool_view`` dequantizes."""
    store = 1 if quantized else DTYPE_BYTES[kv_dtype]
    rows = int(batch) * n_layer * int(pages) * n_head * block_size
    kv = 2 * rows * head_dim * store
    scales = 2 * rows * 4 if quantized else 0
    return kv + scales


def serving_decode_attn_flops(n_layer: int, n_head: int, head_dim: int, *,
                              attended: int, batch: int = 1) -> int:
    """Attention FLOPs of ONE decode step: per (lane, layer, head), the
    single query scores ``attended`` key positions (2 * D FLOPs each:
    the QK dot) and mixes as many value rows (another 2 * D) — 4 * D *
    attended per head.  ``attended`` carries the active-page factor:
    ``W * block_size`` dense, the policy's ``K * block_size`` under a
    sparse window — the compute twin of
    :func:`serving_gather_bytes_per_step`.  The projection GEMMs are
    policy-independent and priced by the MFU ledger, not here."""
    return int(batch) * n_layer * n_head * 4 * head_dim * int(attended)


def zero_shard_dim(shape: Sequence[int], dp: int,
                   taken: Sequence[int] = ()) -> Optional[int]:
    """The dimension mesh.zero_merge_spec would shard over 'data': the
    largest dim (not in ``taken``) divisible by dp; None if nothing fits."""
    best_dim, best = None, 0
    for d, s in enumerate(shape):
        if d in taken:
            continue
        if dp > 1 and s % dp == 0 and s > best:
            best_dim, best = d, s
    return best_dim
