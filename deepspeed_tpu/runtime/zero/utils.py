"""ZeRO compatibility checks.

Reference behavior: deepspeed/runtime/zero/utils.py:36-58 whitelists the
optimizers whose state layout ZeRO knows how to partition, and the engine
refuses unlisted client optimizers unless ``zero_allow_untested_optimizer``
(reference engine.py:681-700).

TPU-native formulation: ZeRO partitioning here is a sharding-spec contract —
an optimizer is ZeRO-supported when it declares its state layout via
``state_spec(param_specs)`` (see ops/adam/fused_adam.py:state_spec). Known
in-tree optimizers are whitelisted by class as well, mirroring the
reference's list.
"""
from deepspeed_tpu.utils.logging import logger


class ZeRORuntimeException(Exception):
    pass


def _supported_classes():
    from deepspeed_tpu.ops.adam.cpu_adam import DeepSpeedCPUAdam
    from deepspeed_tpu.ops.adam.fused_adam import FusedAdam

    return (FusedAdam, DeepSpeedCPUAdam)


def is_zero_supported_optimizer(optimizer) -> bool:
    """An optimizer qualifies if it is a known in-tree class OR declares a
    ``state_spec`` layout (the exact-sharding contract the engine uses)."""
    if isinstance(optimizer, _supported_classes()):
        return True
    return hasattr(optimizer, "state_spec")


def assert_zero_supported_optimizer(optimizer, allow_untested: bool):
    """Engine-side gate (reference engine.py:694-700): raise for unlisted
    client optimizers unless zero_allow_untested_optimizer is set."""
    if is_zero_supported_optimizer(optimizer):
        return
    name = type(optimizer).__name__
    if allow_untested:
        logger.warning(
            f"**** You are using ZeRO with an untested optimizer "
            f"{name!r} (no state_spec); optimizer-state sharding falls "
            f"back to shape matching and may be inexact ****")
        return
    raise ZeRORuntimeException(
        f"You are using ZeRO with an optimizer ({name!r}) that is not "
        f"ZeRO-supported: it neither is a known in-tree optimizer nor "
        f"declares state_spec(). Implement state_spec() or set "
        f"'zero_allow_untested_optimizer': true in the config to proceed "
        f"anyway")
