"""ZeRO config key names/defaults (reference: deepspeed/runtime/zero/constants.py).

Format:
  "zero_optimization": {
    "stage": [0|1|2|3],
    "allgather_partitions": true,
    "allgather_bucket_size": 500000000,
    "reduce_scatter": true,
    "reduce_bucket_size": 500000000,
    "overlap_comm": false,
    "contiguous_gradients": true,
    "cpu_offload": false,
    "elastic_checkpoint": true,
    "load_from_fp32_weights": true
  }

On TPU the bucket sizes and overlap/contiguous flags are accepted for config
compatibility but are advisory: XLA's SPMD partitioner and latency-hiding
scheduler own comm bucketing/overlap.  ``stage`` and ``cpu_offload`` change real
behavior (state sharding spec / host-resident optimizer).
"""

ZERO_OPTIMIZATION = "zero_optimization"

ZERO_OPTIMIZATION_DISABLED = 0
ZERO_OPTIMIZATION_OPTIMIZER_STATES = 1
ZERO_OPTIMIZATION_GRADIENTS = 2
# stage 3 (parameter sharding) is an EXTENSION beyond the reference snapshot
# (its engine.py:720-722 caps at 2): compute params live ZeRO-sharded over
# 'data' and XLA inserts the per-use all-gathers GSPMD-style — ~50 lines of
# sharding specs here vs the reference's later stage3.py
ZERO_OPTIMIZATION_WEIGHTS = 3
MAX_STAGE_ZERO_OPTIMIZATION = ZERO_OPTIMIZATION_WEIGHTS

ZERO_OPTIMIZATION_STAGE = "stage"
ZERO_OPTIMIZATION_STAGE_DEFAULT = ZERO_OPTIMIZATION_DISABLED

ZERO_OPTIMIZATION_ALLGATHER_PARTITIONS = "allgather_partitions"
ZERO_OPTIMIZATION_ALLGATHER_PARTITIONS_DEFAULT = True

ZERO_OPTIMIZATION_REDUCE_SCATTER = "reduce_scatter"
ZERO_OPTIMIZATION_REDUCE_SCATTER_DEFAULT = True

ZERO_OPTIMIZATION_OVERLAP_COMM = "overlap_comm"
ZERO_OPTIMIZATION_OVERLAP_COMM_DEFAULT = False

ZERO_OPTIMIZATION_CONTIGUOUS_GRADIENTS = "contiguous_gradients"
ZERO_OPTIMIZATION_CONTIGUOUS_GRADIENTS_DEFAULT = True

ZERO_OPTIMIZATION_REDUCE_BUCKET_SIZE = "reduce_bucket_size"
ZERO_OPTIMIZATION_REDUCE_BUCKET_SIZE_DEFAULT = 500000000

ZERO_OPTIMIZATION_ALLGATHER_BUCKET_SIZE = "allgather_bucket_size"
ZERO_OPTIMIZATION_ALLGATHER_BUCKET_SIZE_DEFAULT = 500000000
ZERO_OPTIMIZATION_ALLGATHER_BUCKET_SIZE_DEPRECATED = "allgather_size"

ZERO_OPTIMIZATION_CPU_OFFLOAD = "cpu_offload"
ZERO_OPTIMIZATION_CPU_OFFLOAD_DEFAULT = False

ZERO_OPTIMIZATION_ELASTIC_CHECKPOINT = "elastic_checkpoint"
ZERO_OPTIMIZATION_ELASTIC_CHECKPOINT_DEFAULT = True

ZERO_OPTIMIZATION_LOAD_FROM_FP32_WEIGHTS = "load_from_fp32_weights"
ZERO_OPTIMIZATION_LOAD_FROM_FP32_WEIGHTS_DEFAULT = True

# --- ZeRO++-style quantized collectives (arxiv 2306.10209) -----------------
# qgZ: the stage-2 gradient reduce-scatter moves blockwise-int8 + fp32
# scales (quantize -> all_to_all -> local reduce -> dequantize) instead of
# fp32 — ~4x less gradient wire traffic at block 128.
ZERO_OPTIMIZATION_QUANTIZED_GRADIENTS = "quantized_gradients"
ZERO_OPTIMIZATION_QUANTIZED_GRADIENTS_DEFAULT = False

# qwZ: the ZeRO-Offload parameter push all-gathers int8 blocks + scales and
# dequantizes to the compute dtype on device (H2D upload also shrinks).
ZERO_OPTIMIZATION_QUANTIZED_WEIGHTS = "quantized_weights"
ZERO_OPTIMIZATION_QUANTIZED_WEIGHTS_DEFAULT = False

# hierarchical qgZ: two-hop all_to_all — reduce within intra-host groups
# first, then across hosts on re-quantized partials; cross-host (DCN)
# traffic drops to 1/intra_size.  intra_size 0 = auto (gcd of the data
# degree and the local device count; flat when that degenerates).
ZERO_OPTIMIZATION_HIERARCHICAL_ALLREDUCE = "hierarchical_allreduce"
ZERO_OPTIMIZATION_HIERARCHICAL_ALLREDUCE_DEFAULT = False
ZERO_OPTIMIZATION_HIERARCHICAL_INTRA_SIZE = "hierarchical_intra_size"
ZERO_OPTIMIZATION_HIERARCHICAL_INTRA_SIZE_DEFAULT = 0

ZERO_OPTIMIZATION_QUANTIZATION_BLOCK_SIZE = "quantization_block_size"
ZERO_OPTIMIZATION_QUANTIZATION_BLOCK_SIZE_DEFAULT = 128

# --- scheduled stage-3 (ISSUE 8) -------------------------------------------
# stage3_scheduled_gathers: at stage 3, gather each partitioned weight ONCE
# per micro-step as blockwise int8 + fp32 scales along a compile-time
# per-layer-block plan (runtime/zero/stage3.py), persisting the gathered
# weight fwd->bwd (no remat refetch) and freeing it at wgrad.  False keeps
# the implicit path: XLA inserts full-precision gathers at every use site.
ZERO_OPTIMIZATION_STAGE3_SCHEDULED_GATHERS = "stage3_scheduled_gathers"
ZERO_OPTIMIZATION_STAGE3_SCHEDULED_GATHERS_DEFAULT = True

# stage3_prefetch_budget: max bytes of gathered (replicated, compute-dtype)
# weights the scheduled plan may hold live at once — they persist from the
# forward gather to wgrad, so the whole plan's footprint counts.  0 =
# unbounded.  A plan over budget DISARMs back to the implicit XLA path
# (lower peak memory, more wire) with a warning naming the bytes.
ZERO_OPTIMIZATION_STAGE3_PREFETCH_BUDGET = "stage3_prefetch_budget"
ZERO_OPTIMIZATION_STAGE3_PREFETCH_BUDGET_DEFAULT = 0

ZERO_OPTIMIZATION_DEFAULT = ZERO_OPTIMIZATION_DISABLED
