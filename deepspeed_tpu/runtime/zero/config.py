"""Typed ZeRO config object (reference: deepspeed/runtime/zero/config.py:1-106)."""
from deepspeed_tpu.runtime.config_utils import get_scalar_param
from deepspeed_tpu.runtime.zero.constants import (
    MAX_STAGE_ZERO_OPTIMIZATION, ZERO_OPTIMIZATION,
    ZERO_OPTIMIZATION_ALLGATHER_BUCKET_SIZE,
    ZERO_OPTIMIZATION_ALLGATHER_BUCKET_SIZE_DEFAULT,
    ZERO_OPTIMIZATION_ALLGATHER_BUCKET_SIZE_DEPRECATED,
    ZERO_OPTIMIZATION_ALLGATHER_PARTITIONS,
    ZERO_OPTIMIZATION_ALLGATHER_PARTITIONS_DEFAULT,
    ZERO_OPTIMIZATION_CONTIGUOUS_GRADIENTS,
    ZERO_OPTIMIZATION_CONTIGUOUS_GRADIENTS_DEFAULT,
    ZERO_OPTIMIZATION_CPU_OFFLOAD, ZERO_OPTIMIZATION_CPU_OFFLOAD_DEFAULT,
    ZERO_OPTIMIZATION_ELASTIC_CHECKPOINT,
    ZERO_OPTIMIZATION_ELASTIC_CHECKPOINT_DEFAULT,
    ZERO_OPTIMIZATION_LOAD_FROM_FP32_WEIGHTS,
    ZERO_OPTIMIZATION_LOAD_FROM_FP32_WEIGHTS_DEFAULT,
    ZERO_OPTIMIZATION_HIERARCHICAL_ALLREDUCE,
    ZERO_OPTIMIZATION_HIERARCHICAL_ALLREDUCE_DEFAULT,
    ZERO_OPTIMIZATION_HIERARCHICAL_INTRA_SIZE,
    ZERO_OPTIMIZATION_HIERARCHICAL_INTRA_SIZE_DEFAULT,
    ZERO_OPTIMIZATION_OVERLAP_COMM, ZERO_OPTIMIZATION_OVERLAP_COMM_DEFAULT,
    ZERO_OPTIMIZATION_QUANTIZATION_BLOCK_SIZE,
    ZERO_OPTIMIZATION_QUANTIZATION_BLOCK_SIZE_DEFAULT,
    ZERO_OPTIMIZATION_QUANTIZED_GRADIENTS,
    ZERO_OPTIMIZATION_QUANTIZED_GRADIENTS_DEFAULT,
    ZERO_OPTIMIZATION_QUANTIZED_WEIGHTS,
    ZERO_OPTIMIZATION_QUANTIZED_WEIGHTS_DEFAULT,
    ZERO_OPTIMIZATION_REDUCE_BUCKET_SIZE,
    ZERO_OPTIMIZATION_REDUCE_BUCKET_SIZE_DEFAULT,
    ZERO_OPTIMIZATION_REDUCE_SCATTER,
    ZERO_OPTIMIZATION_REDUCE_SCATTER_DEFAULT, ZERO_OPTIMIZATION_STAGE,
    ZERO_OPTIMIZATION_STAGE3_PREFETCH_BUDGET,
    ZERO_OPTIMIZATION_STAGE3_PREFETCH_BUDGET_DEFAULT,
    ZERO_OPTIMIZATION_STAGE3_SCHEDULED_GATHERS,
    ZERO_OPTIMIZATION_STAGE3_SCHEDULED_GATHERS_DEFAULT,
    ZERO_OPTIMIZATION_STAGE_DEFAULT)


class DeepSpeedZeroConfig:
    def __init__(self, param_dict):
        self.stage = None
        self.contiguous_gradients = None
        self.reduce_scatter = None
        self.reduce_bucket_size = None
        self.allgather_partitions = None
        self.allgather_bucket_size = None
        self.overlap_comm = None
        self.cpu_offload = None
        self.elastic_checkpoint = None
        self.load_from_fp32_weights = None
        self.quantized_gradients = None
        self.quantized_weights = None
        self.hierarchical_allreduce = None
        self.hierarchical_intra_size = None
        self.quantization_block_size = None
        self.stage3_scheduled_gathers = None
        self.stage3_prefetch_budget = None

        if ZERO_OPTIMIZATION in param_dict:
            zero_config_dict = param_dict[ZERO_OPTIMIZATION]
            if isinstance(zero_config_dict, bool):
                # legacy: "zero_optimization": true  => stage 1
                zero_config_dict = {ZERO_OPTIMIZATION_STAGE: 1 if zero_config_dict else 0}
        else:
            zero_config_dict = {}
        self._initialize(zero_config_dict)

    def _initialize(self, d):
        self.stage = get_scalar_param(d, ZERO_OPTIMIZATION_STAGE, ZERO_OPTIMIZATION_STAGE_DEFAULT)
        assert self.stage <= MAX_STAGE_ZERO_OPTIMIZATION, (
            f"ZeRO stage {self.stage} not supported; max is {MAX_STAGE_ZERO_OPTIMIZATION} "
            f"(parity with reference snapshot, engine.py:720-722)")
        self.contiguous_gradients = get_scalar_param(
            d, ZERO_OPTIMIZATION_CONTIGUOUS_GRADIENTS, ZERO_OPTIMIZATION_CONTIGUOUS_GRADIENTS_DEFAULT)
        self.reduce_bucket_size = get_scalar_param(
            d, ZERO_OPTIMIZATION_REDUCE_BUCKET_SIZE, ZERO_OPTIMIZATION_REDUCE_BUCKET_SIZE_DEFAULT)
        self.reduce_scatter = get_scalar_param(
            d, ZERO_OPTIMIZATION_REDUCE_SCATTER, ZERO_OPTIMIZATION_REDUCE_SCATTER_DEFAULT)
        self.overlap_comm = get_scalar_param(
            d, ZERO_OPTIMIZATION_OVERLAP_COMM, ZERO_OPTIMIZATION_OVERLAP_COMM_DEFAULT)
        self.allgather_partitions = get_scalar_param(
            d, ZERO_OPTIMIZATION_ALLGATHER_PARTITIONS, ZERO_OPTIMIZATION_ALLGATHER_PARTITIONS_DEFAULT)
        if ZERO_OPTIMIZATION_ALLGATHER_BUCKET_SIZE_DEPRECATED in d:
            self.allgather_bucket_size = d[ZERO_OPTIMIZATION_ALLGATHER_BUCKET_SIZE_DEPRECATED]
        else:
            self.allgather_bucket_size = get_scalar_param(
                d, ZERO_OPTIMIZATION_ALLGATHER_BUCKET_SIZE, ZERO_OPTIMIZATION_ALLGATHER_BUCKET_SIZE_DEFAULT)
        self.cpu_offload = get_scalar_param(
            d, ZERO_OPTIMIZATION_CPU_OFFLOAD, ZERO_OPTIMIZATION_CPU_OFFLOAD_DEFAULT)
        self.elastic_checkpoint = get_scalar_param(
            d, ZERO_OPTIMIZATION_ELASTIC_CHECKPOINT, ZERO_OPTIMIZATION_ELASTIC_CHECKPOINT_DEFAULT)
        self.load_from_fp32_weights = get_scalar_param(
            d, ZERO_OPTIMIZATION_LOAD_FROM_FP32_WEIGHTS, ZERO_OPTIMIZATION_LOAD_FROM_FP32_WEIGHTS_DEFAULT)
        self.quantized_gradients = get_scalar_param(
            d, ZERO_OPTIMIZATION_QUANTIZED_GRADIENTS,
            ZERO_OPTIMIZATION_QUANTIZED_GRADIENTS_DEFAULT)
        self.quantized_weights = get_scalar_param(
            d, ZERO_OPTIMIZATION_QUANTIZED_WEIGHTS,
            ZERO_OPTIMIZATION_QUANTIZED_WEIGHTS_DEFAULT)
        self.hierarchical_allreduce = get_scalar_param(
            d, ZERO_OPTIMIZATION_HIERARCHICAL_ALLREDUCE,
            ZERO_OPTIMIZATION_HIERARCHICAL_ALLREDUCE_DEFAULT)
        self.hierarchical_intra_size = int(get_scalar_param(
            d, ZERO_OPTIMIZATION_HIERARCHICAL_INTRA_SIZE,
            ZERO_OPTIMIZATION_HIERARCHICAL_INTRA_SIZE_DEFAULT))
        self.quantization_block_size = int(get_scalar_param(
            d, ZERO_OPTIMIZATION_QUANTIZATION_BLOCK_SIZE,
            ZERO_OPTIMIZATION_QUANTIZATION_BLOCK_SIZE_DEFAULT))
        assert self.quantization_block_size > 0, \
            "zero_optimization.quantization_block_size must be positive"
        self.stage3_scheduled_gathers = get_scalar_param(
            d, ZERO_OPTIMIZATION_STAGE3_SCHEDULED_GATHERS,
            ZERO_OPTIMIZATION_STAGE3_SCHEDULED_GATHERS_DEFAULT)
        self.stage3_prefetch_budget = int(get_scalar_param(
            d, ZERO_OPTIMIZATION_STAGE3_PREFETCH_BUDGET,
            ZERO_OPTIMIZATION_STAGE3_PREFETCH_BUDGET_DEFAULT))
        assert self.stage3_prefetch_budget >= 0, \
            "zero_optimization.stage3_prefetch_budget must be >= 0 (0 = " \
            "unbounded)"

    def repr(self):
        return dict(stage=self.stage,
                    contiguous_gradients=self.contiguous_gradients,
                    reduce_scatter=self.reduce_scatter,
                    reduce_bucket_size=self.reduce_bucket_size,
                    allgather_partitions=self.allgather_partitions,
                    allgather_bucket_size=self.allgather_bucket_size,
                    overlap_comm=self.overlap_comm,
                    cpu_offload=self.cpu_offload,
                    elastic_checkpoint=self.elastic_checkpoint,
                    load_from_fp32_weights=self.load_from_fp32_weights,
                    quantized_gradients=self.quantized_gradients,
                    quantized_weights=self.quantized_weights,
                    hierarchical_allreduce=self.hierarchical_allreduce,
                    hierarchical_intra_size=self.hierarchical_intra_size,
                    quantization_block_size=self.quantization_block_size,
                    stage3_scheduled_gathers=self.stage3_scheduled_gathers,
                    stage3_prefetch_budget=self.stage3_prefetch_budget)

    def __repr__(self):
        return str(self.repr())
