"""Scheduled ZeRO stage-3: the compile-time parameter-gather plan.

The reference snapshot stops at stage 2 (engine.py:720-722); our stage 3
stores compute params ZeRO-sharded over 'data'.  Left implicit, XLA
inserts a full-precision all-gather at every use site — and, under a
remat'd backward, fetches each weight AGAIN for the recompute: roughly
8x the wire of a scheduled int8 gather-once path.

This module plans the explicit alternative in the DeepCompile spirit
(arxiv 2504.09983: prefetch/release decided schedule-side, at compile
time, not by runtime hooks): group the partitioned parameter leaves into
per-layer blocks in forward order, price each block's quantized wire
(int8 payload + fp32 scales, byte-exact against quantization.
block_layout) and its gathered footprint, and decide ONCE — at arming
time, never in the step path — whether the plan fits the configured
``zero_optimization.stage3_prefetch_budget``.  The engine lowers the
plan as program structure: one ``custom_collectives.quantized_all_gather``
per leaf, emitted in block order ahead of the compute that consumes it,
so XLA's latency-hiding scheduler overlaps block k+1's gather with
block k's compute; the gathered weight then persists fwd->bwd as a vjp
residual (no backward refetch) and is donated/freed at wgrad.

Everything here is pure shape math — no devices, no jax arrays — so
plans are buildable (and testable) on any host, and the analytic bytes
agree with runtime/comm_accounting.py's collective model.
"""
from dataclasses import dataclass, field
from typing import List, Optional, Sequence

from deepspeed_tpu.runtime.comm_accounting import all_gather_bytes
from deepspeed_tpu.runtime.quantization import (DEFAULT_BLOCK_SIZE,
                                                block_layout)

_DTYPE_BYTES = {"float32": 4, "bfloat16": 2, "float16": 2, "float64": 8}


@dataclass
class GatherLeaf:
    """One partitioned parameter leaf in the plan."""
    name: str               # tree path, e.g. "h_3/attn/qkv"
    index: int              # flat leaf index in the params pytree
    shape: tuple
    shard_dim: int          # dim the ZeRO spec shards over 'data'
    elements: int
    gathered_bytes: int     # replicated footprint in the compute dtype
    wire_bytes: int         # int8 blocks + fp32 scales each rank SENDS


@dataclass
class GatherBlock:
    """One per-layer gather unit: leaves that become live together."""
    key: str
    leaves: List[GatherLeaf] = field(default_factory=list)

    @property
    def gathered_bytes(self) -> int:
        return sum(l.gathered_bytes for l in self.leaves)

    @property
    def wire_bytes(self) -> int:
        return sum(l.wire_bytes for l in self.leaves)


@dataclass
class GatherPlan:
    """The compile-time schedule: ``blocks`` in forward order, plus the
    leaf indices that stay replicated (too small/indivisible to shard —
    nothing to gather)."""
    blocks: List[GatherBlock]
    replicated: List[int]
    dp: int
    block_size: int
    param_dtype: str

    @property
    def n_gathered_leaves(self) -> int:
        return sum(len(b.leaves) for b in self.blocks)

    @property
    def gathered_bytes(self) -> int:
        """Peak transient footprint of the gathered weights: they persist
        from their forward gather to their wgrad (vjp residuals), so the
        whole plan is live at once — the number the prefetch budget
        bounds."""
        return sum(b.gathered_bytes for b in self.blocks)

    @property
    def wire_bytes_per_gather(self) -> int:
        return sum(b.wire_bytes for b in self.blocks)

    def within_budget(self, budget: int) -> bool:
        """budget <= 0 means unbounded (armed)."""
        return budget <= 0 or self.gathered_bytes <= budget

    def report(self) -> dict:
        """The docs/metrics rendering: per-block bytes + totals, for
        prefetch-budget sizing from the peak-bytes numbers."""
        return {
            "dp": self.dp,
            "block_size": self.block_size,
            "param_dtype": self.param_dtype,
            "n_blocks": len(self.blocks),
            "n_gathered_leaves": self.n_gathered_leaves,
            "n_replicated_leaves": len(self.replicated),
            "peak_gathered_bytes": self.gathered_bytes,
            "wire_bytes_per_gather": self.wire_bytes_per_gather,
            "blocks": [{"key": b.key,
                        "leaves": [l.name for l in b.leaves],
                        "gathered_bytes": b.gathered_bytes,
                        "wire_bytes": b.wire_bytes}
                       for b in self.blocks],
        }


def block_key(name: str) -> str:
    """Layer-block key of a leaf path: its first path component — for the
    repo's models ("h_3/attn/qkv", "wte") that is exactly the per-layer
    grouping the forward consumes in order."""
    return name.split("/", 1)[0]


def leaf_wire_bytes(elements: int, dp: int, block_size: int) -> int:
    """int8 + fp32-scale bytes ONE rank sends to gather one leaf: its
    local shard quantized, through comm_accounting's own ring all-gather
    model — the agreement with param_gather_collectives' qwZ pricing is
    structural, not a re-derived formula."""
    if dp <= 1:
        return 0
    _, nb, npad = block_layout(elements // dp, block_size)
    return all_gather_bytes(dp * npad, 1, dp) + all_gather_bytes(dp * nb,
                                                                 4, dp)


def build_gather_plan(names: Sequence[str], shapes: Sequence[tuple],
                      shard_dims: Sequence[Optional[int]], dp: int, *,
                      block_size: int = DEFAULT_BLOCK_SIZE,
                      param_dtype: str = "float32") -> GatherPlan:
    """Build the plan from flat leaf facts, in pytree (= forward) order.

    ``shard_dims[i]`` is the dim the ZeRO param spec shards over 'data'
    (None = replicated leaf, excluded from the plan).  Consecutive leaves
    sharing a :func:`block_key` form one block, so the emitted gather
    order is the forward traversal of the model tree.
    """
    es = _DTYPE_BYTES.get(param_dtype, 4)
    blocks: List[GatherBlock] = []
    replicated: List[int] = []
    for i, (name, shape, dim) in enumerate(zip(names, shapes, shard_dims)):
        n = 1
        for d in shape:
            n *= int(d)
        if dim is None or dp <= 1 or shape[dim] % dp != 0:
            replicated.append(i)
            continue
        key = block_key(name)
        if not blocks or blocks[-1].key != key:
            blocks.append(GatherBlock(key=key))
        blocks[-1].leaves.append(GatherLeaf(
            name=name, index=i, shape=tuple(shape), shard_dim=dim,
            elements=n, gathered_bytes=n * es,
            wire_bytes=leaf_wire_bytes(n, dp, block_size)))
    return GatherPlan(blocks=blocks, replicated=replicated, dp=dp,
                      block_size=block_size, param_dtype=param_dtype)
