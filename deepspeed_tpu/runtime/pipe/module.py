"""PipelineModule — express a model as a partitionable layer list.

Reference behavior: deepspeed/runtime/pipe/module.py:23-575 (LayerSpec lazy
construction, TiedLayerSpec shared weights, uniform/parameters/type:regex
stage partitioning, per-layer seeds, activation checkpointing every N layers).

TPU-first formulation: the module is functional — it produces a params pytree
keyed per layer ("layer_00", ..., tied params under "tied_<key>") and pure
apply functions per stage. The same object serves three executors:
- the base DeepSpeedEngine (sequential apply -> the DataParallelSchedule
  baseline, and the parity reference for pipeline tests),
- the PipelineEngine (per-stage apply on stage submeshes),
- user code (module.forward_stage for custom drivers).
"""
import re

from deepspeed_tpu.runtime.utils import partition_balanced, partition_uniform
from deepspeed_tpu.utils.logging import logger


class LayerSpec:
    """Lazily-built layer: stores the constructor + args so stages only pay
    for what they build (reference module.py:23-68).

    partition_spec: optional callable ``params -> pytree of PartitionSpec``
    declaring this layer's tensor-parallel layout over the mesh 'model'
    axis. This is what makes PP x TP (true 3D) expressible: the reference
    threads an external Megatron mpu through its pipeline grid
    (pipe/topology.py:246-249); here each layer declares its own sharding
    and the stage submeshes honor it."""

    def __init__(self, typename, *module_args, partition_spec=None,
                 forward_fn=None, **module_kwargs):
        self.typename = typename
        self.module_args = module_args
        self.module_kwargs = module_kwargs
        self.partition_spec = partition_spec
        # optional custom apply (module, params, x) -> y, same contract as
        # TiedLayerSpec.forward_fn (e.g. an untied LM head reusing the
        # embedding module's matmul without sharing its params)
        self.forward_fn = forward_fn

    def build(self, log=False):
        if log:
            logger.info(f"building {repr(self)}")
        return self.typename(*self.module_args, **self.module_kwargs)

    def __repr__(self):
        name = getattr(self.typename, "__name__", str(self.typename))
        return f"LayerSpec({name})"


class TiedLayerSpec(LayerSpec):
    """Layer whose params are shared with every other spec carrying the same
    key — e.g. embedding reused as the LM head (reference module.py:71-83)."""

    def __init__(self, key, typename, *module_args, forward_fn=None,
                 tied_weight_attr="embedding", partition_spec=None,
                 **module_kwargs):
        super().__init__(typename, *module_args,
                         partition_spec=partition_spec, **module_kwargs)
        self.key = key
        self.forward_fn = forward_fn
        self.tied_weight_attr = tied_weight_attr


def _is_flax_module(obj):
    try:
        import flax.linen as nn

        return isinstance(obj, nn.Module)
    except ImportError:  # pragma: no cover
        return False


class _Layer:
    """Uniform init/apply wrapper over flax modules and plain callables."""

    def __init__(self, obj, index, param_key, forward_fn=None, spec_fn=None):
        import inspect

        self.obj = obj
        self.index = index
        self.param_key = param_key        # None => stateless
        self.forward_fn = forward_fn
        # TP layout provider: LayerSpec.partition_spec wins, else a
        # param_partition_spec method on the built module itself
        self.spec_fn = spec_fn or getattr(obj, "param_partition_spec", None)
        self.is_flax = _is_flax_module(obj)
        self.type_name = type(obj).__name__
        self.tied_key = None
        self.is_tied_owner = False
        # inspect once instead of catching TypeError per call — a retry
        # would silently swallow genuine TypeErrors from the train path
        self.accepts_train = False
        self.has_losses = False   # set by init(): layer sows aux losses
        if self.is_flax:
            try:
                sig = inspect.signature(type(obj).__call__)
                self.accepts_train = "train" in sig.parameters
            except (TypeError, ValueError):  # pragma: no cover
                pass

    def _flax_apply(self, params, x, rng, train, mutable=None):
        kwargs = {"train": train} if self.accepts_train else {}
        if mutable is not None:
            kwargs["mutable"] = mutable
        return self.obj.apply({"params": params}, x,
                              rngs={"dropout": rng}, **kwargs)

    def init(self, rng, x):
        if self.is_flax:
            kwargs = {"train": False} if self.accepts_train else {}
            variables = self.obj.init({"params": rng, "dropout": rng}, x,
                                      **kwargs)
            params = variables.get("params", {})
            # does this layer sow auxiliary losses (MoE load balance)?
            # Decided once here so dense layers never pay the mutable-apply
            # path and aux stays a Python 0.0 through dense pipelines
            self.has_losses = bool(variables.get("losses"))
            return params, self._flax_apply(params, x, rng, train=False)
        # stateless callable
        return None, self.obj(x)

    def apply(self, params, x, rng, train):
        if self.forward_fn is not None:
            return self.forward_fn(self.obj, params, x)
        if self.is_flax:
            return self._flax_apply(params, x, rng, train)
        return self.obj(x)

    def apply_aux(self, params, x, rng, train):
        """apply + this layer's sown auxiliary loss (flax 'losses'
        collection — e.g. the MoE load-balance term), Python 0.0 when the
        layer sows none (decided at init)."""
        if self.forward_fn is not None or not self.is_flax \
                or not getattr(self, "has_losses", False):
            return self.apply(params, x, rng, train), 0.0
        out, col = self._flax_apply(params, x, rng, train,
                                    mutable=["losses"])
        from deepspeed_tpu.moe import sum_moe_losses

        return out, sum_moe_losses(col.get("losses", {}))


class PipelineModule:
    """Layer-list model, partitionable across pipeline stages.

    Args:
        layers: sequence of LayerSpec / TiedLayerSpec / flax modules /
            callables, applied in order.
        loss_fn: (final_output, batch) -> (scalar_loss, metrics dict).
        num_stages: pipeline depth (defaults to the mesh 'pipe' axis when
            driven by an engine; 1 otherwise).
        partition_method: 'uniform' | 'parameters' | 'type:<regex>'
            (reference module.py:348-403).
        input_fn: batch -> first-stage input (default: batch['x']).
        activation_checkpoint_interval: remat every N layers in the
            sequential path (reference module.py:292-346).
        seed_layers: pin each layer's init to PRNGKey(base_seed + index),
            reproducible independent of the engine rng (reference
            module.py:85 seed_layers). Off or on, every layer always folds
            in its own index so same-shaped layers init differently.
    """

    def __init__(self, layers, loss_fn=None, num_stages=None, topology=None,
                 partition_method="parameters", input_fn=None,
                 activation_checkpoint_interval=0, seed_layers=False,
                 base_seed=1234):
        self.specs = list(layers)
        self.loss_fn = loss_fn
        self.num_stages = num_stages
        self.client_topology = topology
        self.partition_method = partition_method
        self.input_fn = input_fn or (lambda batch: batch["x"])
        self.activation_checkpoint_interval = activation_checkpoint_interval
        self.seed_layers = seed_layers
        self.base_seed = base_seed

        self._layers = []
        tied_owner = {}
        for i, spec in enumerate(self.specs):
            if isinstance(spec, TiedLayerSpec):
                layer = _Layer(spec.build(), i, f"tied_{spec.key}",
                               spec.forward_fn, spec_fn=spec.partition_spec)
                layer.tied_key = spec.key
                if spec.key not in tied_owner:
                    tied_owner[spec.key] = i
                layer.is_tied_owner = tied_owner[spec.key] == i
            elif isinstance(spec, LayerSpec):
                layer = _Layer(spec.build(), i, f"layer_{i:02d}",
                               spec.forward_fn,
                               spec_fn=spec.partition_spec)
            else:
                layer = _Layer(spec, i,
                               f"layer_{i:02d}" if _is_flax_module(spec)
                               else None)
            self._layers.append(layer)
        self._param_counts = None   # per-layer param count, set by init
        self._parts = None          # stage boundaries, lazy

    # ------------------------------------------------------------------
    # engine model contract
    # ------------------------------------------------------------------
    def init(self, rng, batch):
        import jax

        params = {}
        x = self.input_fn(batch)
        counts = []
        for layer in self._layers:
            # every layer folds in its index: same-shaped layers must never
            # initialize identically (the reference gets this for free because
            # torch's global RNG advances per layer, module.py:85).
            # seed_layers additionally pins each layer to base_seed+index,
            # independent of the engine rng (reference seed_layers semantics:
            # layer init reproducible regardless of what ran before it).
            if self.seed_layers:
                lrng = jax.random.PRNGKey(self.base_seed + layer.index)
            else:
                lrng = jax.random.fold_in(rng, layer.index)
            if layer.param_key is not None and layer.param_key in params:
                # tied reuse: params exist; just advance the activation
                x = layer.apply(params[layer.param_key], x, lrng, train=False)
                counts.append(0)
                continue
            p, x_new = layer.init(lrng, x)
            x = x_new
            if p is None or (hasattr(p, "__len__") and len(p) == 0):
                layer.param_key = None
                counts.append(0)
            else:
                params[layer.param_key] = p
                counts.append(sum(int(l.size)
                                  for l in jax.tree_util.tree_leaves(p)))
        self._param_counts = counts
        return params

    def loss(self, params, batch, rng, train=True):
        assert self.loss_fn is not None, "PipelineModule needs loss_fn to train"
        out, aux = self.forward_full(params, batch, rng, train,
                                     return_aux=True)
        loss, metrics = self.loss_fn(out, batch)
        if train and not isinstance(aux, float):
            # layer-sown auxiliary losses (MoE load balance) join the
            # training objective; eval loss stays comparable to dense
            loss = loss + aux
            metrics = dict(metrics, aux_loss=aux, loss=loss)
        return loss, metrics

    def forward_full(self, params, batch, rng, train, return_aux=False):
        """Sequential (non-pipelined) forward through all layers, with
        activation checkpointing every N layers when configured."""
        import jax

        x = self.input_fn(batch)
        aux = 0.0
        interval = self.activation_checkpoint_interval
        if interval and train:
            for start in range(0, len(self._layers), interval):
                seg = self._layers[start:start + interval]
                # segments without sown losses keep the plain (x-only)
                # remat body so a dense model's aux stays the Python 0.0
                # sentinel (jax.checkpoint would trace a constant into an
                # Array and fake an aux term downstream)
                if any(l.has_losses for l in seg):
                    def run_aux(x, seg=seg):
                        return self._apply_range(params, x, rng, train, seg,
                                                 collect_aux=True)

                    x, seg_aux = jax.checkpoint(run_aux)(x)
                    aux = aux + seg_aux
                else:
                    def run(x, seg=seg):
                        return self._apply_range(params, x, rng, train, seg)

                    x = jax.checkpoint(run)(x)
        else:
            x, aux = self._apply_range(params, x, rng, train, self._layers,
                                       collect_aux=True)
        return (x, aux) if return_aux else x

    def _apply_range(self, params, x, rng, train, layers, collect_aux=False):
        import jax

        aux = 0.0
        for layer in layers:
            # dropout keys fold in layer.index unconditionally: identical
            # same-shaped layers must not share dropout masks (seed_layers
            # only controls the *init* seed, matching reference module.py:85
            # where torch's global RNG advances per layer regardless)
            lrng = jax.random.fold_in(rng, layer.index)
            p = params[layer.param_key] if layer.param_key is not None else None
            if collect_aux:
                x, layer_aux = layer.apply_aux(p, x, lrng, train)
                aux = aux + layer_aux
            else:
                x = layer.apply(p, x, lrng, train)
        return (x, aux) if collect_aux else x

    def forward_stage(self, params, x, stage_id, rng, train, num_stages=None,
                      return_aux=False):
        """Apply this stage's layer range to x (PipelineEngine hot path).
        return_aux: also return the stage-local sum of sown auxiliary
        losses (the PipelineEngine's backward adds them to the objective —
        an aux loss at stage k contributes a DIRECT gradient at stage k,
        it never flows through the activation cotangents)."""
        start, stop = self.stage_bounds(stage_id, num_stages)
        return self._apply_range(params, x, rng, train,
                                 self._layers[start:stop],
                                 collect_aux=return_aux)

    # ------------------------------------------------------------------
    # partitioning (reference module.py:348-403)
    # ------------------------------------------------------------------
    def stage_bounds(self, stage_id, num_stages=None):
        parts = self.partition_layers(num_stages)
        return parts[stage_id], parts[stage_id + 1]

    def partition_layers(self, num_stages=None):
        num_stages = num_stages or self.num_stages or 1
        if self._parts is not None and len(self._parts) == num_stages + 1:
            return self._parts
        n = len(self._layers)
        method = (self.partition_method or "uniform").lower()
        if method == "uniform":
            parts = partition_uniform(n, num_stages)
        elif method == "parameters":
            assert self._param_counts is not None, \
                "call init() before parameter-balanced partitioning"
            # tied reuses count 0 so the owner stage carries the weight
            parts = partition_balanced(self._param_counts, num_stages)
        elif method.startswith("type:"):
            pattern = method.split(":", 1)[1]
            weights = [1 if re.search(pattern, l.type_name, re.IGNORECASE)
                       else 0 for l in self._layers]
            parts = partition_balanced(weights, num_stages)
        elif method == "profile":
            raise NotImplementedError(
                "profile partitioning is not implemented (parity: reference "
                "module.py:372 also raises)")
        else:
            raise KeyError(f"unknown partition method {self.partition_method}")
        self._parts = parts
        return parts

    def validate_chunking(self, stages, virtual_stages):
        """Blocker string (for the engine's DISARMED warning) if this layer
        list cannot be split into ``stages * virtual_stages`` interleaved
        chunks, else None. Chunk partitioning reuses partition_layers with
        the chunk count as the stage count, so every chunk must be
        non-empty and the layer count must divide evenly — a ragged split
        would put unequal work on the same device's chunks and break the
        ~1/v bubble model."""
        chunks = stages * virtual_stages
        n = len(self._layers)
        if n % chunks != 0:
            return (f"layer count {n} is not divisible by pipe x "
                    f"virtual_stages = {stages} x {virtual_stages}")
        return None

    def has_tied_layers(self):
        """True when any layer shares params via TiedLayerSpec."""
        return any(l.tied_key is not None for l in self._layers)

    # ------------------------------------------------------------------
    # introspection used by the engine
    # ------------------------------------------------------------------
    @property
    def layers(self):
        return self._layers

    def stage_param_keys(self, stage_id, num_stages=None):
        """Param-tree keys owned by a stage. Tied params belong to every
        stage that uses them (the engine keeps them in sync)."""
        start, stop = self.stage_bounds(stage_id, num_stages)
        keys = []
        for layer in self._layers[start:stop]:
            if layer.param_key is not None and layer.param_key not in keys:
                keys.append(layer.param_key)
        return keys

    def tied_groups(self, num_stages=None):
        """{tie_key: sorted list of stage_ids using it} for multi-stage ties
        (reference module.py:420-474)."""
        num_stages = num_stages or self.num_stages or 1
        groups = {}
        for layer in self._layers:
            if layer.tied_key is None or layer.param_key is None:
                continue
            for s in range(num_stages):
                start, stop = self.stage_bounds(s, num_stages)
                if start <= layer.index < stop:
                    groups.setdefault(layer.tied_key, set()).add(s)
        return {k: sorted(v) for k, v in groups.items() if len(v) > 1}

    def param_partition_spec(self, params):
        """Per-layer TP specs over the mesh 'model' axis.

        Works on any subset of the params dict (a stage's subtree): each
        top-level key is resolved to its owning layer and that layer's
        spec_fn (LayerSpec.partition_spec or the module's own
        param_partition_spec) produces the specs; layers without one are
        replicated. This is the hook that gives pipeline models real TP —
        the reference's analog is the mpu slice group carried by
        PipeModelDataParallelTopology (topology.py:246-249)."""
        import jax
        from jax.sharding import PartitionSpec as P

        by_key = {}
        for layer in self._layers:
            if layer.param_key is None or layer.param_key in by_key:
                continue
            if layer.spec_fn is not None:
                by_key[layer.param_key] = layer.spec_fn
        out = {}
        for key, subtree in params.items():
            fn = by_key.get(key)
            if fn is None:
                out[key] = jax.tree_util.tree_map(lambda _: P(), subtree)
            else:
                out[key] = fn(subtree)
        return out

    def num_params(self, params):
        import jax

        return sum(int(x.size) for x in jax.tree_util.tree_leaves(params))

    def mpu(self):
        return None
