"""PipelineModule — placeholder until the pipeline engine lands.

Real implementation: LayerSpec/TiedLayerSpec partitioning over pipe stages
(reference: deepspeed/runtime/pipe/module.py:85).
"""


class LayerSpec:
    def __init__(self, typename, *module_args, **module_kwargs):
        self.typename = typename
        self.module_args = module_args
        self.module_kwargs = module_kwargs

    def build(self):
        return self.typename(*self.module_args, **self.module_kwargs)


class TiedLayerSpec(LayerSpec):
    def __init__(self, key, typename, *module_args, forward_fn=None,
                 tied_weight_attr="embedding", **module_kwargs):
        super().__init__(typename, *module_args, **module_kwargs)
        self.key = key
        self.forward_fn = forward_fn
        self.tied_weight_attr = tied_weight_attr


class PipelineModule:
    def __init__(self, *args, **kwargs):
        raise NotImplementedError(
            "PipelineModule is implemented in the pipeline milestone")

    def mpu(self):
        return None
