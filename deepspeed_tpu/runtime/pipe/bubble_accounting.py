"""Analytic pipeline-bubble accounting — tick simulation of compiled
instruction streams.

The schedule compiler (runtime/pipe/schedule.py) emits per-stage flat
instruction streams; this module replays them against a cost model with the
SAME queue semantics the engine uses (in-order execution per stage, a Recv
blocks until the matching Send's payload is ready), and reports, per
physical stage: busy time, idle fraction, and the peak number of live
activation buffers. No device is touched — the numbers are exact
deterministic functions of (schedule, cost model), so schedule wins are
assertable in tier-1 tests on CPU, the same proof idiom as
runtime/comm_accounting.py for collective bytes.

The default cost model matches THIS implementation's jits. A schedule
compiled WITHOUT stash slots pays the zero-bubble remat tax: the fused
backward (b=2) is one forward recompute (1) plus the combined grad math
(1); the split dgrad/wgrad passes each re-run the stage forward inside
their own jit, so d = w = 1.5 and d + w = b + f — remat ZB-H1 moves MORE
total work per micro than the fused schedules. Its bubble FRACTION still
lands lowest (utilization is high), but compare ``makespan`` for
throughput: at pipe=4/gas=8 that model gives zb-h1 makespan 36.5 vs
1f1b 33 — under always-remat the extra recompute outweighs the bubble it
fills (M*f extra work vs a constant (S-1)(f+b-(f+d-w)) saving), matching
the CPU-mesh measurement in BENCH_NOTES. A schedule compiled with
``stash=True`` (bounded activation stashing — the engine runs the
forward once and both split passes consume its stashed vjp residuals)
defaults to ``CostModel.stash()`` (d = w = 1, d + w = b): zb-h1 becomes
a genuine throughput win, makespan 27 vs 33 at the same point, paid for
in stash memory (``peak_live_stash`` per stage). With f == b
(``CostModel.equal_fwd_bwd()``) the plain 1F1B simulation reproduces the
closed form (S-1)/(M+S-1) exactly (the round-5 BENCH_NOTES numbers:
0.20 at pipe=2, 0.43 at pipe=4, gas=4).

A stream that can never satisfy one of its Recvs makes the simulation
wedge; that raises ``DeadlockError`` naming the blocked stages — the
deadlock-freedom check the test suite runs over every schedule × topology.
"""
from dataclasses import dataclass
from typing import Dict, List, Optional

from deepspeed_tpu.runtime.pipe import schedule as sched_lib


class DeadlockError(RuntimeError):
    """The instruction streams cannot make progress (a Recv whose Send can
    never execute)."""


@dataclass
class CostModel:
    """Abstract per-instruction durations (arbitrary time units).

    fwd/bwd apply to ForwardPass/BackwardPass; dgrad/wgrad to the
    zero-bubble split passes — defaults include each split pass's own
    forward recompute (see module docstring; d = w = f/2 + (b-f)/2 + f/2
    ... i.e. half the grad math plus a full recompute = 1.5 at f=1, b=2).
    p2p is the transfer latency added between a Send and the matching
    Recv's readiness. Loads and host-side bookkeeping are free."""
    fwd: float = 1.0
    bwd: float = 2.0
    dgrad: float = 1.5
    wgrad: float = 1.5
    p2p: float = 0.0

    @classmethod
    def equal_fwd_bwd(cls):
        """f == b == 1 — the model behind the classic (S-1)/(M+S-1)
        ideal-bubble formula; split passes get half the grad math (0.25)
        plus their own recompute (0.5) each, per the same remat rule."""
        return cls(fwd=1.0, bwd=1.0, dgrad=0.75, wgrad=0.75)

    @classmethod
    def stash(cls):
        """d == w == 1 — the activation-STASHING variant (arXiv
        2401.10241's assumption): the forward runs ONCE and saves its vjp
        residuals, so neither split pass recomputes it and
        d + w == b == 2 (no extra total work vs the fused backward).
        This is the default model for schedules compiled with
        ``stash=True`` and the model under which zb-h1 turns from a
        makespan loss (36.5 vs 33 at pipe=4/gas=8) into a win (27)."""
        return cls(fwd=1.0, bwd=2.0, dgrad=1.0, wgrad=1.0)


@dataclass
class _StageSim:
    time: float = 0.0
    busy: float = 0.0
    pc: int = 0
    live: int = 0
    peak_live: int = 0
    stash_live: int = 0
    peak_stash: int = 0


def simulate(compiled, costs: Optional[CostModel] = None) -> dict:
    """Replay a CompiledSchedule; returns the bubble report dict.

    Keys: schedule, micro_batches, stages, virtual_stages, makespan,
    busy (per stage), idle_fraction (per stage), bubble_fraction
    (aggregate: 1 - sum(busy) / (stages * makespan)), peak_live_buffers
    (per stage, activation slots held simultaneously), peak_live_stash
    (per stage, stashed-forward residual sets held simultaneously —
    lifetime ForwardPass -> BackwardWeightPass; all zero unless the
    schedule was compiled with stash slots), total_instructions,
    p2p_transfers (count of send/recv edges crossed per step).

    With no explicit cost model, a stash-compiled schedule defaults to
    ``CostModel.stash()`` (no recompute in either split pass) and every
    other schedule to the remat-honest ``CostModel()`` — the report
    always prices what the engine actually executes.
    """
    stashed = bool(getattr(compiled, "stash", False))
    costs = costs or (CostModel.stash() if stashed else CostModel())
    S = compiled.stages
    C = compiled.num_chunks
    # a chunk is ~1/v of a stage's layers, so per-chunk compute scales
    # down by virtual_stages (total work per stage is schedule-invariant)
    inv_v = 1.0 / compiled.virtual_stages
    streams = compiled.streams
    sims = [_StageSim() for _ in range(S)]
    # per (global chunk, kind) FIFO of payload-ready times
    act_q: Dict[int, List[float]] = {q: [] for q in range(C)}
    grad_q: Dict[int, List[float]] = {q: [] for q in range(C)}
    p2p_transfers = 0

    def cost_of(cmd):
        if isinstance(cmd, sched_lib.ForwardPass):
            return costs.fwd * inv_v
        if isinstance(cmd, sched_lib.BackwardGradPass):
            return costs.dgrad * inv_v
        if isinstance(cmd, sched_lib.BackwardWeightPass):
            return costs.wgrad * inv_v
        if isinstance(cmd, sched_lib.BackwardPass):
            return costs.bwd * inv_v
        return 0.0

    while True:
        progressed, alldone = False, True
        for s, sim in enumerate(sims):
            if sim.pc >= len(streams[s]):
                continue
            alldone = False
            cmd = streams[s][sim.pc]
            g = getattr(cmd, "chunk_id", 0) * S + s
            if isinstance(cmd, sched_lib.RecvActivation):
                if not act_q[g]:
                    continue                       # blocked on the producer
                sim.time = max(sim.time, act_q[g].pop(0))
                sim.live += 1
                sim.peak_live = max(sim.peak_live, sim.live)
            elif isinstance(cmd, sched_lib.RecvGrad):
                if not grad_q[g]:
                    continue
                sim.time = max(sim.time, grad_q[g].pop(0))
            elif isinstance(cmd, sched_lib.SendActivation):
                act_q[g + 1].append(sim.time + costs.p2p)
                p2p_transfers += 1
            elif isinstance(cmd, sched_lib.SendGrad):
                grad_q[g - 1].append(sim.time + costs.p2p)
                p2p_transfers += 1
            elif isinstance(cmd, sched_lib.LoadMicroBatch):
                if g == 0:
                    sim.live += 1
                    sim.peak_live = max(sim.peak_live, sim.live)
            else:
                c = cost_of(cmd)
                sim.time += c
                sim.busy += c
                if stashed and isinstance(cmd, sched_lib.ForwardPass):
                    sim.stash_live += 1
                    sim.peak_stash = max(sim.peak_stash, sim.stash_live)
                if isinstance(cmd, (sched_lib.BackwardPass,
                                    sched_lib.BackwardWeightPass)):
                    sim.live -= 1
                    if stashed and isinstance(cmd,
                                              sched_lib.BackwardWeightPass):
                        sim.stash_live -= 1
            sim.pc += 1
            progressed = True
        if alldone:
            break
        if not progressed:
            blocked = [s for s, sim in enumerate(sims)
                       if sim.pc < len(streams[s])]
            raise DeadlockError(
                f"pipeline schedule '{compiled.name}' deadlocked: stages "
                f"{blocked} blocked at "
                f"{[streams[s][sims[s].pc] for s in blocked]}")

    makespan = max(sim.time for sim in sims) or 1.0
    busy = [sim.busy for sim in sims]
    return {
        "schedule": compiled.name,
        "micro_batches": compiled.micro_batches,
        "stages": S,
        "virtual_stages": compiled.virtual_stages,
        "cost_model": {"fwd": costs.fwd, "bwd": costs.bwd,
                       "dgrad": costs.dgrad, "wgrad": costs.wgrad,
                       "p2p": costs.p2p},
        "makespan": makespan,
        "busy": busy,
        "idle_fraction": [1.0 - b / makespan for b in busy],
        "bubble_fraction": 1.0 - sum(busy) / (S * makespan),
        "peak_live_buffers": [sim.peak_live for sim in sims],
        "peak_live_stash": [sim.peak_stash for sim in sims],
        "stash": stashed,
        "declared_buffers": list(compiled.num_buffers),
        "declared_stash_slots": list(getattr(compiled, "num_stash_slots",
                                             [0] * len(compiled.num_buffers))),
        "total_instructions": sum(len(st) for st in streams),
        "p2p_transfers": p2p_transfers,
    }


def bubble_report(schedule, micro_batches, stages, virtual_stages=1,
                  costs: Optional[CostModel] = None, stash=False) -> dict:
    """Compile + simulate in one call (the tools/tests entry point)."""
    compiled = sched_lib.compile_schedule(
        schedule, micro_batches, stages, virtual_stages, stash=stash)
    return simulate(compiled, costs)


# instruction kinds a telemetry trace can carry back into the simulator
_TRACE_INSTRUCTIONS = {
    cls.__name__: cls for cls in (
        sched_lib.LoadMicroBatch, sched_lib.ForwardPass,
        sched_lib.BackwardPass, sched_lib.BackwardGradPass,
        sched_lib.BackwardWeightPass, sched_lib.SendActivation,
        sched_lib.RecvActivation, sched_lib.SendGrad, sched_lib.RecvGrad)}


def replay_trace(events, compiled, costs: Optional[CostModel] = None,
                 lane_prefix="stage") -> dict:
    """MEASURED bubble report: rebuild per-stage instruction streams from
    a telemetry trace (the PipelineEngine interpreter records one span
    per executed compiled instruction, lane ``stage<N>``, args
    (chunk_id, micro_id)) and replay them through the SAME tick
    simulation :func:`simulate` runs on the compiled plan.

    This is the cross-check the analytic numbers need to be trusted:
    ``simulate(compiled)`` prices what the schedule compiler *planned*;
    ``replay_trace(events, compiled)`` prices what the engine *actually
    executed*, reconstructed from its own trace.  An interpreter that
    reorders, drops or duplicates work diverges here — faithful
    execution reproduces the analytic idle fractions exactly (the tier-1
    tolerance test at pipe=4/gas=8).

    Raises ``ValueError`` on a trace with no pipeline spans — replaying
    an empty stream would report a perfect zero-instruction pipeline.
    """
    S = compiled.stages
    streams = [[] for _ in range(S)]
    n = 0
    for ev in events:
        lane = ev.get("lane", "")
        if not lane.startswith(lane_prefix):
            continue
        try:
            s = int(lane[len(lane_prefix):])
        except ValueError:
            continue
        cls = _TRACE_INSTRUCTIONS.get(ev.get("name"))
        if cls is None or not (0 <= s < S):
            continue
        chunk = ev.get("a0", -1)
        micro = ev.get("a1", -1)
        streams[s].append(cls(buffer_id=0,
                              chunk_id=chunk if chunk >= 0 else 0,
                              micro_id=micro))
        n += 1
    if n == 0:
        raise ValueError(
            "replay_trace: no pipeline instruction spans in the trace "
            f"(lanes '{lane_prefix}<N>'); was telemetry armed for the "
            "train_batch being replayed, or did the trace ring drop "
            "them (raise telemetry.trace_capacity)?")
    traced = sched_lib.CompiledSchedule(
        f"{compiled.name}-trace", compiled.micro_batches, S,
        compiled.virtual_stages, streams, compiled.num_buffers,
        stash=compiled.stash)
    report = simulate(traced, costs)
    report["replayed_instructions"] = n
    return report


def ideal_1f1b_bubble(micro_batches, stages):
    """Closed form (S-1)/(M+S-1) — valid for the equal_fwd_bwd cost model;
    kept as the cross-check anchor for the simulator."""
    return (stages - 1) / (micro_batches + stages - 1)
