"""PipelineEngine — placeholder until the pipeline milestone."""
from deepspeed_tpu.runtime.engine import DeepSpeedEngine


class PipelineEngine(DeepSpeedEngine):
    def __init__(self, *args, **kwargs):
        raise NotImplementedError(
            "PipelineEngine is implemented in the pipeline milestone")
