"""PipelineEngine — pipeline-parallel training over stage submeshes.

Reference behavior: deepspeed/runtime/pipe/engine.py:45-1169 (instruction
dispatch `_exec_schedule` :1148, train_batch :244, eval_batch :320, p2p via
2-rank broadcast groups).

TPU-native architecture: the full device mesh (pipe, data, model) is split
into one submesh per stage; each stage's params/optimizer state live only on
its submesh (pipeline memory scaling), with ZeRO sharding over the submesh's
'data' axis on top. The engine executes the SAME declarative instruction
schedules as the reference (runtime/pipe/schedule.py), but:

- SendActivation/RecvActivation/SendGrad/RecvGrad are `jax.device_put`
  transfers between adjacent submeshes (ICI neighbor copies — the analog of
  the reference's broadcast-pair p2p, pipe/p2p.py:31-58);
- ForwardPass/BackwardPass are per-stage jitted calls; the single-controller
  runtime dispatches them asynchronously, so stages on disjoint devices
  overlap exactly as the 1F1B schedule intends;
- BackwardPass recomputes the stage forward inside the jit (vjp-with-remat) —
  activation checkpointing per stage, matching the reference's
  activation-checkpoint-every-stage default;
- ReduceGrads is implicit: XLA inserts the data-axis psum inside the
  backward jit (the reference's bucketed allreduce, engine.py:852-868);
- ReduceTiedGrads sums accumulated tied-param grads across the stages in the
  tie group and redistributes, so identical optimizer updates keep tied
  copies in sync (reference module.py:405-418).

fp16 dynamic loss scaling runs host-side here (the schedule is host-driven
anyway): per-stage finite checks combine on host, overflow skips the step
and halves the scale (reference fp16/loss_scaler.py:79-170 semantics).
"""
import logging
import os
import pickle
from collections import deque
from typing import NamedTuple

import numpy as np

from deepspeed_tpu.parallel import mesh as mesh_lib
from deepspeed_tpu.runtime.engine import DeepSpeedEngine
from deepspeed_tpu.runtime.pipe import schedule as sched_lib
from deepspeed_tpu.runtime.pipe.module import PipelineModule
from deepspeed_tpu.runtime.pipe.topology import (PipelineParallelGrid,
                                                 PipeModelDataParallelTopology)
from deepspeed_tpu.utils.logging import log_dist, logger


class StageState(NamedTuple):
    params: object      # compute-dtype params for this stage's layers
    master: object      # fp32 master (None in fp32 mode)
    opt_state: object   # optimizer state over master
    accum: object       # fp32 grad accumulator


class _MfuJitProxy:
    """Transparent stage-jit wrapper for the compiled-program registry
    and the MFU/measured-memory ledgers: on FIRST dispatch it captures a
    ShapeDtypeStruct tree of the real args and registers a lazy
    lower+compile with telemetry/programs.py (always — the registry is
    the seam tools/graftlint/program_lint.py reads, and registration is
    a shape capture + dict insert, no compile) plus telemetry/mfu.py and
    runtime/memory_accounting.py when those ledgers are armed (the two
    share ONE compiled object per jit), then calls through.  Attribute
    access (``.lower`` for the HLO contract tests) passes through to the
    wrapped jit."""

    # __weakref__: jax.eval_shape / linear_util cache weakref their
    # callables (the stash-size estimate abstract-evals fwd_stash
    # through this proxy)
    __slots__ = ("fn", "name", "mfu", "mem", "mesh", "calls",
                 "programs", "contract", "_registered", "__weakref__")

    def __init__(self, fn, name, mfu, mesh, calls, mem=None,
                 programs=None, contract=None):
        self.fn = fn
        self.name = name
        self.mfu = mfu
        self.mem = mem
        self.mesh = mesh
        self.calls = calls
        self.programs = programs
        self.contract = contract
        self._registered = False

    def __call__(self, *args):
        if not self._registered:
            import jax

            # register only from a CONCRETE dispatch: under an abstract
            # evaluation (the stash-size estimate eval_shapes fwd_stash
            # through this proxy) the args are tracers with no
            # shardings — capturing them would re-lower the UNsharded
            # whole-stage program, inflating per-device cost/memory and
            # breaking the per-device HFU premise
            if not any(isinstance(l, jax.core.Tracer)
                       for l in jax.tree_util.tree_leaves(args)):
                self._registered = True
                from deepspeed_tpu.telemetry import (register_by_shape,
                                                     register_program)

                register_program(self.programs, self.name, self.fn, args,
                                 mesh=self.mesh, contract=self.contract,
                                 calls_per_step=self.calls)
                register_by_shape(self.mfu, self.name, self.fn, args,
                                  mesh=self.mesh,
                                  calls_per_step=self.calls)
                if self.mem is not None:
                    from deepspeed_tpu.runtime import \
                        memory_accounting as mem_acc

                    mem_acc.register_by_shape(
                        self.mem, self.name, self.fn, args,
                        mesh=self.mesh, calls_per_step=self.calls)
        return self.fn(*args)

    def __getattr__(self, item):
        return getattr(self.fn, item)


class PipelineEngine(DeepSpeedEngine):
    # per-stage params have no cross-stage 'data' replica to vote over —
    # _arm_integrity keeps the SENTINELS armed (they ride the host
    # loss/grad-norm this interpreter already fetches) and DISARM-warns
    # only the vote (ISSUE 13/16); inherited by any PipelineEngine
    # subclass, unlike a class-name check
    _integrity_armable = False
    """Training engine for PipelineModule models. Use train_batch/eval_batch;
    forward/backward/step are disabled (reference pipe/engine.py:1090-1098)."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        assert isinstance(self.module, PipelineModule), \
            "PipelineEngine requires a PipelineModule model"
        # own program-registry namespace: pipe jits are per-(chunk, kind),
        # not the base engine's micro/apply programs (nothing registered
        # yet — base-engine registration happens at first dispatch)
        self._programs.engine = "pipe"
        if self.zero_optimization_stage() > 2:
            # stage-3 parameter partitioning (and its scheduled gather
            # plan) lives in the base engine: here each stage's params
            # are already stage-local on a submesh, and the per-chunk
            # jits have no cross-stage axis to gather over.  Downgrade
            # to stage 2 (optimizer + gradient sharding still apply)
            # instead of dying on an assert.
            log_dist(
                "PipelineEngine: ZeRO stage-3 scheduled gathers DISARMED "
                "— parameters are already partitioned per pipeline stage "
                "and the stage-3 gather plan has no cross-stage 'data' "
                "shard to gather; running ZeRO stage 2 (optimizer state "
                "+ gradient sharding over 'data')", ranks=[0],
                level=logging.WARNING)
            self._config.zero_config.stage = 2
            self._config.zero_optimization_stage = 2

        import jax

        self.num_stages = mesh_lib.pp_size(self.mesh)
        self.micro_batches = self.gradient_accumulation_steps()
        self._arm_schedule()
        self.num_chunks = self.num_stages * self.virtual_stages
        # the module partitions by CHUNK: with v=1 chunks == stages, with
        # interleaving each physical stage owns v non-contiguous chunks
        self.module.num_stages = self.num_chunks

        topo = PipeModelDataParallelTopology(
            num_pp=self.num_stages, num_mp=self.mp_world_size,
            num_dp=self.dp_world_size)
        self.grid = PipelineParallelGrid(topology=topo, rank=0,
                                         virtual_stages=self.virtual_stages)

        # one submesh per stage: mesh.devices is (pipe, data, seq, model)
        self._submeshes = []
        for s in range(self.num_stages):
            self._submeshes.append(
                jax.sharding.Mesh(self.mesh.devices[s],
                                  ("data", "seq", "model")))

        self.stage_states = None          # list[StageState] per CHUNK, lazy
        self._stage_shardings = None
        self._stage_jits = None
        self._compiled_schedule = None    # CompiledSchedule, lazy
        self._last_p2p_bytes = 0          # measured p2p volume, last batch
        self._p2p_edge_bytes = {}         # global chunk -> (act, grad) bytes
        # zb-h1 activation stashing (resolved lazily by _arm_stash once
        # shapes are known: the budget check needs per-micro stash bytes)
        self._stash_armed = False
        self._stash_blockers = []
        self._stash_bytes_per_chunk = None  # per-micro vjp-residual bytes

        if self.progressive_layer_drop is not None:
            # base engine injects pld_theta into flat batches; the pipeline
            # engine's per-stage jits never see the batch dict mid-stage
            log_dist(
                "PipelineEngine: progressive_layer_drop DISARMED — layers "
                "run undropped (theta would have to thread through every "
                "per-stage jit and re-partition stage compute; unsupported "
                "with pipeline parallelism — use the base engine for PLD)",
                ranks=[0], level=logging.WARNING)
            self.progressive_layer_drop = None
        # host-side loss scaling: the schedule is host-driven, so the shared
        # host DynamicLossScaler owns the policy (hysteresis, window, floor)
        if self.fp16_enabled():
            from deepspeed_tpu.runtime.fp16.loss_scaler import CreateLossScaler

            args_ls = dict(self._config.dynamic_loss_scale_args or {})
            args_ls.setdefault("init_scale",
                               self._config.initial_dynamic_scale)
            self._pipe_scaler = CreateLossScaler(
                static_loss_scale=self._config.loss_scale or 0,
                dynamic_scale_args=args_ls)
        else:
            from deepspeed_tpu.runtime.fp16.loss_scaler import LossScaler

            self._pipe_scaler = LossScaler(scale=1)
        self._host_skipped = 0

        log_dist(
            f"PipelineEngine: stages={self.num_stages} "
            f"micro_batches={self.micro_batches} dp={self.dp_world_size} "
            f"mp={self.mp_world_size}", ranks=[0])

    def _arm_schedule(self):
        """Resolve the requested pipeline schedule against its blockers.

        Sets self.pipe_schedule (effective), self.virtual_stages, and
        self._schedule_blockers. A blocked request falls back to plain
        1f1b with a DISARMED warning naming every blocker (the repo's
        armed-or-warns discipline, same as OneBitAdam/qgZ arming)."""
        from deepspeed_tpu.runtime.constants import (PIPELINE_SCHEDULE,
                                                     PIPELINE_VIRTUAL_STAGES)
        from deepspeed_tpu.runtime.pipe import schedule as sched_lib

        pcfg = self._config.pipeline
        requested = pcfg[PIPELINE_SCHEDULE]
        req_v = int(pcfg[PIPELINE_VIRTUAL_STAGES])
        S, gas = self.num_stages, self.micro_batches
        self.requested_schedule = requested
        blockers = []

        if requested == sched_lib.SCHEDULE_INTERLEAVED:
            if S < 2:
                blockers.append("pipe=1 (nothing to interleave)")
            if req_v < 2:
                blockers.append(f"virtual_stages={req_v} (needs >= 2)")
            if S >= 2 and gas % S != 0:
                blockers.append(
                    f"gradient_accumulation_steps={gas} not divisible by "
                    f"pipe={S} (the Megatron interleaving order requires it)")
            if req_v >= 2:
                why = self.module.validate_chunking(S, req_v)
                if why:
                    blockers.append(why)
        elif requested == sched_lib.SCHEDULE_ZB_H1:
            if S < 2:
                blockers.append("pipe=1 (no bubble to fill)")
            if self.module.has_tied_layers():
                blockers.append(
                    "tied layers present (deferred wgrads would interleave "
                    "with the cross-stage tied-grad reduction)")
            if req_v > 1:
                log_dist(
                    f"PipelineEngine: pipeline.virtual_stages={req_v} is "
                    f"ignored by the zb-h1 schedule (wgrad deferral fills "
                    f"the bubble instead of chunk interleaving)",
                    ranks=[0], level=logging.WARNING)
        elif req_v > 1:
            log_dist(
                f"PipelineEngine: pipeline.virtual_stages={req_v} has no "
                f"effect with schedule=1f1b; set schedule=interleaved",
                ranks=[0], level=logging.WARNING)

        if blockers:
            log_dist(
                f"PipelineEngine: schedule '{requested}' DISARMED — "
                f"falling back to 1f1b ({'; '.join(blockers)})",
                ranks=[0], level=logging.WARNING)
            self.pipe_schedule = sched_lib.SCHEDULE_1F1B
            self.virtual_stages = 1
        else:
            self.pipe_schedule = requested
            self.virtual_stages = req_v \
                if requested == sched_lib.SCHEDULE_INTERLEAVED else 1
        self._schedule_blockers = blockers

    # ------------------------------------------------------------------
    # disabled base API (reference pipe/engine.py:1090-1098)
    # ------------------------------------------------------------------
    def forward(self, *a, **k):
        raise RuntimeError("PipelineEngine: use train_batch()/eval_batch()")

    def backward(self, *a, **k):
        raise RuntimeError("PipelineEngine: use train_batch()/eval_batch()")

    def step(self, *a, **k):
        raise RuntimeError("PipelineEngine: use train_batch()/eval_batch()")

    @property
    def skipped_steps(self):
        return self._host_skipped

    def loss_scale(self):
        return self._pipe_scaler.cur_scale

    def is_first_stage(self):
        """True: the single controller owns every stage, including stage 0
        (reference semantics — 'does this rank host the first stage' — are
        per-rank; here one process IS all ranks, so both predicates hold
        and first/last-stage-only work like data loading and loss handling
        runs on this process)."""
        return True

    def is_last_stage(self):
        return True

    # ------------------------------------------------------------------
    # state construction
    # ------------------------------------------------------------------
    def _stage_zero_shardings(self, submesh, params_template):
        """NamedShardings for one stage: params take the layers' TP specs
        over the submesh 'model' axis (PP x TP — the reference's 3D grid,
        pipe/topology.py:246-249), master/opt/accum additionally
        ZeRO-sharded over the submesh 'data' axis."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        stage = self.zero_optimization_stage()
        dp = submesh.shape["data"]

        tp_spec = self.module.param_partition_spec(params_template)
        is_p = lambda x: isinstance(x, P)  # noqa: E731
        param_sh = jax.tree_util.tree_map(
            lambda s: NamedSharding(submesh, s), tp_spec, is_leaf=is_p)
        if stage == 0:
            zero_spec = tp_spec
            zero = param_sh
        else:
            zero_spec = jax.tree_util.tree_map(
                lambda s, l: mesh_lib.zero_merge_spec(s, l, dp),
                tp_spec, params_template, is_leaf=is_p)
            zero = jax.tree_util.tree_map(
                lambda s: NamedSharding(submesh, s), zero_spec, is_leaf=is_p)

        # optimizer-state shardings (same policy as the base engine,
        # runtime/engine.py:_build_shardings): the optimizer declares its
        # state layout via state_spec; fallback matches param shapes
        rep = NamedSharding(submesh, P())
        opt_template = jax.eval_shape(self.optimizer.init_state,
                                      params_template)
        flat_opt, opt_def = jax.tree_util.tree_flatten(opt_template)
        if hasattr(self.optimizer, "state_spec"):
            spec_tree = self.optimizer.state_spec(zero_spec)
            spec_flat = jax.tree_util.tree_flatten(
                spec_tree, is_leaf=lambda x: x is None or isinstance(x, P))[0]
            assert len(spec_flat) == len(flat_opt)
            opt_sh_flat = [rep if s is None else NamedSharding(submesh, s)
                           for s in spec_flat]
        else:
            from deepspeed_tpu.runtime.utils import opt_shardings_by_shape

            zero_flat = jax.tree_util.tree_leaves(zero)
            shapes = [tuple(l.shape) for l in
                      jax.tree_util.tree_leaves(params_template)]
            opt_sh_flat = opt_shardings_by_shape(
                flat_opt, shapes, zero_flat, rep)
        opt_sh = opt_def.unflatten(opt_sh_flat)
        return param_sh, zero, opt_sh

    def _ensure_pipe_state(self, sample_micro):
        if self.stage_states is not None:
            return
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        # init full params on host once (layer by layer), then scatter each
        # stage's slice to its submesh
        init_rng, self._pipe_rng = jax.random.split(self._init_rng)
        # init on the HOST cpu backend: local_devices()[0] would be an
        # accelerator chip and the full fp32 model + a whole-model forward
        # would defeat per-stage memory scaling
        try:
            host_dev = jax.local_devices(backend="cpu")[0]
        except RuntimeError:  # pragma: no cover - cpu backend always exists
            host_dev = jax.local_devices()[0]
        with jax.default_device(host_dev):
            full_params = self.module.init(init_rng, sample_micro)
        full_params = jax.tree_util.tree_map(
            lambda l: np.asarray(jax.device_get(l), dtype=np.float32),
            full_params)
        parts = self.module.partition_layers(self.num_chunks)
        logger.info(f"pipeline partition boundaries: {parts} "
                    f"(chunks={self.num_chunks}, v={self.virtual_stages})")

        self.stage_states = []
        self._stage_shardings = []
        for s in range(self.num_chunks):
            submesh = self._chunk_mesh(s)
            keys = self.module.stage_param_keys(s)
            p32 = {k: full_params[k] for k in keys}
            rep, zero, opt_sh = self._stage_zero_shardings(submesh, p32)

            master = jax.tree_util.tree_map(
                lambda l, sh: jax.device_put(l, sh), p32, zero) \
                if self.mixed_precision else None
            params = jax.tree_util.tree_map(
                lambda l, sh: jax.device_put(
                    np.asarray(l, dtype=self.compute_dtype), sh), p32, rep)
            opt_src = master if self.mixed_precision else \
                jax.tree_util.tree_map(lambda l, sh: jax.device_put(l, sh),
                                       p32, zero)
            with jax.set_mesh(submesh):
                # out_shardings pins the declared layout — unconstrained,
                # XLA would pick its own and void the ZeRO partitioning
                opt_state = jax.jit(self.optimizer.init_state,
                                    out_shardings=opt_sh)(opt_src)
                accum = jax.tree_util.tree_map(
                    lambda l: jnp.zeros(l.shape, jnp.float32), p32)
                accum = jax.tree_util.tree_map(
                    lambda l, sh: jax.device_put(l, sh), accum, zero)
            self.stage_states.append(StageState(
                params=params, master=master, opt_state=opt_state,
                accum=accum))
            self._stage_shardings.append((rep, zero, opt_sh))
        self._build_stage_jits()
        self._arm_stash(sample_micro)
        n = sum(self.module.num_params(st.params) for st in self.stage_states)
        log_dist(f"Pipeline state initialized: {n/1e6:.1f}M params over "
                 f"{self.num_stages} stages x {self.virtual_stages} chunks "
                 f"(schedule={self.pipe_schedule})", ranks=[0])

    def _chunk_mesh(self, chunk):
        """Submesh of the physical stage owning global model chunk
        ``chunk`` (chunk q lives on stage q % pipe — grid.chunk_owner_
        stage; with v=1 this is the identity)."""
        return self._submeshes[self.grid.chunk_owner_stage(chunk)]

    def _build_stage_jits(self):
        import jax
        import jax.numpy as jnp

        module = self.module
        S = self.num_chunks
        gas = self.micro_batches
        zb = self.pipe_schedule == sched_lib.SCHEDULE_ZB_H1
        loss_fn = module.loss_fn
        # does any layer sow aux losses (MoE)? decided by module.init()
        self._module_has_aux = any(l.has_losses for l in module._layers)

        self._stage_jits = []
        for s in range(S):
            is_last = s == S - 1

            def fwd(params, x, rng, s=s):
                return module.forward_stage(params, x, s, rng, train=True)

            def fwd_aux(params, x, rng, s=s):
                # stage forward + stage-local sown aux losses (MoE load
                # balance): backward adds them to the objective directly
                return module.forward_stage(params, x, s, rng, train=True,
                                            return_aux=True)

            def fwd_loss(params, x, rng, batch, s=s):
                out, aux = module.forward_stage(params, x, s, rng,
                                                train=True, return_aux=True)
                loss, _ = loss_fn(out, batch)
                return loss, aux

            rep_sh, zero_sh, opt_sh = self._stage_shardings[s]

            def accum_add(accum, gp, zero_sh=zero_sh):
                # pin the ZeRO layout: without the constraint XLA is free to
                # re-lay-out the donated accumulator after the add
                return jax.tree_util.tree_map(
                    lambda a, g, sh: jax.lax.with_sharding_constraint(
                        a + g.astype(jnp.float32), sh),
                    accum, gp, zero_sh)

            # NOTE: closures bind loop-locals via default args — a bare
            # reference would late-bind to the LAST stage's function.
            # backward + gradient accumulation are ONE jit (donated accum):
            # the host-driven schedule pays one dispatch per BackwardPass
            # instead of two, and the grads never materialize outside the
            # accumulator.
            def bwd_last(params, accum, x, rng, batch, scale,
                         fwd_loss=fwd_loss, accum_add=accum_add):
                def scaled(params, x):
                    loss, aux = fwd_loss(params, x, rng, batch)
                    # reported loss includes the stage-local aux term so the
                    # two executors of a PipelineModule (this engine and the
                    # sequential base-engine path via module.loss) agree.
                    # Mid-stage aux terms enter gradients only — a truly
                    # global reported objective would need an extra host
                    # reduction per micro-batch.
                    with_aux = loss.astype(jnp.float32) + aux
                    return with_aux * scale / gas, with_aux

                # integer x (token ids reaching the last stage when pipe=1)
                # is not differentiable and its grad is never sent anywhere
                if jnp.issubdtype(jnp.asarray(x).dtype, jnp.inexact):
                    (_, loss), grads = jax.value_and_grad(
                        scaled, argnums=(0, 1), has_aux=True)(params, x)
                    gp, gx = grads
                else:
                    (_, loss), gp = jax.value_and_grad(
                        scaled, argnums=0, has_aux=True)(params, x)
                    gx = jnp.zeros((), jnp.float32)
                return accum_add(accum, gp), gx, loss

            def bwd_mid(params, accum, x, rng, gy, scale, fwd_aux=fwd_aux,
                        accum_add=accum_add):
                def f(p, x):
                    y, aux = fwd_aux(p, x, rng)
                    return y, jnp.asarray(aux, jnp.float32)

                (_, aux), vjp = jax.vjp(f, params, x)
                # aux cotangent scale/gas: the stage-local aux losses enter
                # the objective with the same loss scaling as the last
                # stage's loss term
                gp, gx = vjp((gy, (scale / gas).astype(jnp.float32)))
                # raw aux returned so train_batch can report the FULL
                # objective (last-stage loss + every stage's aux)
                return accum_add(accum, gp), gx, aux

            def sqnorm(accum):
                total = jnp.float32(0.0)
                finite = jnp.asarray(True)
                for g in jax.tree_util.tree_leaves(accum):
                    g32 = g.astype(jnp.float32)
                    total += jnp.sum(jnp.square(g32))
                    finite &= jnp.all(jnp.isfinite(g32))
                return total, finite

            optimizer = self.optimizer
            mixed = self.mixed_precision
            cdtype = self.compute_dtype

            def apply_step(state: StageState, lr, inv_scale, clip_factor,
                           rep_sh=rep_sh, zero_sh=zero_sh, opt_sh=opt_sh):
                grads = jax.tree_util.tree_map(
                    lambda g: g * inv_scale * clip_factor, state.accum)
                target = state.master if mixed else state.params
                new_master, new_opt = optimizer.update(
                    grads, state.opt_state, target, lr=lr)
                new_opt = jax.tree_util.tree_map(
                    jax.lax.with_sharding_constraint, new_opt, opt_sh)
                # pin layouts: params keep the TP spec (replicated over
                # 'data' — the ZeRO all-gather happens here, reference
                # stage2.py:1556-1590), master stays ZeRO-sharded.
                # Unconstrained, XLA would leave params data-sharded and
                # re-gather on every forward.
                if mixed:
                    new_master = jax.tree_util.tree_map(
                        jax.lax.with_sharding_constraint, new_master, zero_sh)
                    new_params = jax.tree_util.tree_map(
                        lambda l, sh: jax.lax.with_sharding_constraint(
                            l.astype(cdtype), sh), new_master, rep_sh)
                else:
                    new_params = jax.tree_util.tree_map(
                        jax.lax.with_sharding_constraint, new_master, rep_sh)
                    new_master = None
                zero_accum = jax.tree_util.tree_map(
                    lambda l, sh: jax.lax.with_sharding_constraint(
                        jnp.zeros_like(l), sh), state.accum, zero_sh)
                return StageState(params=new_params, master=new_master,
                                  opt_state=new_opt, accum=zero_accum)

            def eval_fwd(params, x, rng, s=s):
                return module.forward_stage(params, x, s, rng, train=False)

            def eval_loss(params, x, rng, batch, s=s):
                out = module.forward_stage(params, x, s, rng, train=False)
                loss, _ = loss_fn(out, batch)
                return loss

            # --- zero-bubble split backward (ZB-H1, arXiv 2401.10241) ---
            # dgrad stays on the critical path (it unblocks the upstream
            # stage), wgrad is deferred into bubble slots; both recompute
            # the stage forward (per-stage remat, same as the fused
            # backward) under the SAME rng so dropout masks agree, and the
            # identical cotangents make dgrad+wgrad = the fused vjp.
            def bwd_last_dgrad(params, x, rng, batch, scale,
                               fwd_loss=fwd_loss):
                def scaled(x_):
                    loss, aux = fwd_loss(params, x_, rng, batch)
                    with_aux = loss.astype(jnp.float32) + aux
                    return with_aux * scale / gas, with_aux

                if jnp.issubdtype(jnp.asarray(x).dtype, jnp.inexact):
                    (_, loss), gx = jax.value_and_grad(
                        scaled, has_aux=True)(x)
                else:
                    _, loss = scaled(x)
                    gx = jnp.zeros((), jnp.float32)
                return gx, loss

            def bwd_last_wgrad(params, accum, x, rng, batch, scale,
                               fwd_loss=fwd_loss, accum_add=accum_add):
                def scaled(p):
                    loss, aux = fwd_loss(p, x, rng, batch)
                    return (loss.astype(jnp.float32) + aux) * scale / gas

                gp = jax.grad(scaled)(params)
                return accum_add(accum, gp)

            def bwd_mid_dgrad(params, x, rng, gy, scale, fwd_aux=fwd_aux):
                def f(x_):
                    y, aux = fwd_aux(params, x_, rng)
                    return y, jnp.asarray(aux, jnp.float32)

                (_, aux), vjp = jax.vjp(f, x)
                (gx,) = vjp((gy, (scale / gas).astype(jnp.float32)))
                return gx, aux

            def bwd_mid_wgrad(params, accum, x, rng, gy, scale,
                              fwd_aux=fwd_aux, accum_add=accum_add):
                def f(p):
                    y, aux = fwd_aux(p, x, rng)
                    return y, jnp.asarray(aux, jnp.float32)

                _, vjp = jax.vjp(f, params)
                (gp,) = vjp((gy, (scale / gas).astype(jnp.float32)))
                return accum_add(accum, gp)

            # --- zb-h1 + activation stashing ------------------------------
            # The forward runs ONCE per (chunk, micro) and returns its vjp
            # closure — a jax.tree_util.Partial whose array leaves are the
            # saved residuals (every checkpoint_name'd intermediate the
            # model's remat_policy would have kept, and then some): that
            # Partial IS the stash, crossing the jit boundary as a pytree.
            # dgrad evaluates the cotangent chain only (XLA DCEs the
            # param-transpose work), wgrad replays the chain into the
            # param grads — neither pass recomputes the forward, which is
            # exactly CostModel.stash()'s d = w = 1.  wgrad DONATES the
            # stash (and accum): the residual buffers free in place on the
            # dgrad->wgrad handoff instead of surviving to the end of the
            # batch.  rng/dropout consistency is free — there is only one
            # forward, so dgrad and wgrad share its masks by construction.
            def fwd_stash_mid(params, x, rng, fwd_aux=fwd_aux):
                def f(p, x_):
                    y, aux = fwd_aux(p, x_, rng)
                    return y, jnp.asarray(aux, jnp.float32)

                (y, aux), stash = jax.vjp(f, params, x)
                return y, aux, stash

            def fwd_stash_last(params, x, rng, batch, scale,
                               fwd_loss=fwd_loss):
                def scaled(p, x_):
                    loss, aux = fwd_loss(p, x_, rng, batch)
                    with_aux = loss.astype(jnp.float32) + aux
                    return with_aux * scale / gas, with_aux

                _, stash, loss = jax.vjp(scaled, params, x, has_aux=True)
                return loss, stash

            def bwd_dgrad_last_stash(stash):
                _, gx = stash(jnp.float32(1.0))
                return gx

            def bwd_dgrad_mid_stash(stash, gy, scale):
                _, gx = stash((gy, (scale / gas).astype(jnp.float32)))
                return gx

            def bwd_wgrad_last_stash(stash, accum, accum_add=accum_add):
                gp, _ = stash(jnp.float32(1.0))
                return accum_add(accum, gp)

            def bwd_wgrad_mid_stash(stash, accum, gy, scale,
                                    accum_add=accum_add):
                gp, _ = stash((gy, (scale / gas).astype(jnp.float32)))
                return accum_add(accum, gp)

            submesh = self._chunk_mesh(s)
            jits = {
                "fwd": jax.jit(fwd),
                "bwd_last": jax.jit(bwd_last, donate_argnums=(1,))
                if is_last else None,
                "bwd_mid": jax.jit(bwd_mid, donate_argnums=(1,)),
                "sqnorm": jax.jit(sqnorm),
                "apply_step": jax.jit(apply_step, donate_argnums=(0,)),
                "eval_fwd": jax.jit(eval_fwd),
                "eval_loss": jax.jit(eval_loss) if is_last else None,
                "mean_scalar": jax.jit(lambda ls: jnp.stack(ls).mean()),
                "mesh": submesh,
            }
            if zb:
                jits["bwd_dgrad"] = jax.jit(bwd_last_dgrad) if is_last \
                    else jax.jit(bwd_mid_dgrad)
                jits["bwd_wgrad"] = (
                    jax.jit(bwd_last_wgrad, donate_argnums=(1,)) if is_last
                    else jax.jit(bwd_mid_wgrad, donate_argnums=(1,)))
                # stash twins (compiled only if _arm_stash arms: jax.jit
                # wrappers are lazy).  dgrad must NOT donate the stash —
                # the deferred wgrad is its second consumer.
                jits["fwd_stash"] = jax.jit(
                    fwd_stash_last if is_last else fwd_stash_mid)
                jits["bwd_dgrad_stash"] = jax.jit(
                    bwd_dgrad_last_stash if is_last else bwd_dgrad_mid_stash)
                jits["bwd_wgrad_stash"] = jax.jit(
                    bwd_wgrad_last_stash if is_last else bwd_wgrad_mid_stash,
                    donate_argnums=(0, 1))
            tel = self._telemetry
            mem = self._memacct
            # every compute jit is proxied: the program registry is
            # always on (registration is a first-dispatch shape capture,
            # no compile — the disarmed step stays bit-identical with
            # zero extra compiles); MFU/memory ledgers ride the same
            # proxy only when armed.  fwd/bwd kinds run once per micro
            # per chunk, the reductions/apply once per optimizer step.
            per_micro = {"fwd", "fwd_stash", "bwd_last", "bwd_mid",
                         "bwd_dgrad", "bwd_wgrad", "bwd_dgrad_stash",
                         "bwd_wgrad_stash"}
            mfu = tel.mfu if tel is not None else None
            n_accum = len(jax.tree_util.tree_leaves(zero_sh))
            jits = {
                k: _MfuJitProxy(v, f"chunk{s}:{k}", mfu, submesh,
                                gas if k in per_micro else 1.0,
                                mem=mem, programs=self._programs,
                                contract=self._stage_jit_contract(
                                    k, is_last, n_accum))
                if (v is not None and k != "mesh") else v
                for k, v in jits.items()}
            self._stage_jits.append(jits)

    def _stage_jit_contract(self, kind, is_last, n_accum):
        """The HLO contract one stage-jit kind declares to the program
        registry (telemetry/programs.py): every compute jit is pure
        device work; a non-last forward's boundary activation leaves the
        stage in the compute dtype (an f32 boundary would double the p2p
        bytes pipeline_report() budgets per edge); backward kinds donate
        the grad accumulator; the zb-stash wgrad additionally donates the
        residual stash and writes every new-accum output into donated
        memory (no copy on the dgrad->wgrad handoff)."""
        import numpy as np

        contract = {"host_transfer_free": True}
        if kind == "fwd" and not is_last:
            short = {"float32": "f32", "bfloat16": "bf16",
                     "float16": "f16", "float64": "f64"}
            name = np.dtype(self.compute_dtype).name
            contract["boundary_dtypes"] = [short.get(name, name)]
        if kind in ("bwd_last", "bwd_mid", "bwd_wgrad"):
            contract["donates_argnums"] = (1,)
        if kind == "apply_step":
            contract["donates_argnums"] = (0,)
        if kind == "bwd_wgrad_stash":
            contract["donates_argnums"] = (0, 1)
            contract["outputs_aliased"] = n_accum
        return contract

    def _stash_bytes_estimate(self, sample_micro):
        """Per-chunk, per-micro stash bytes (the vjp-residual leaves of one
        fwd_stash call), by abstract evaluation — no device work.  Chains
        the chunk output shapes forward exactly as the executor does.
        Also records the FULL fwd_stash output footprint per chunk
        (stash + boundary activation/loss) in
        ``_stash_out_bytes_per_chunk`` — the analytic side of the
        memory-accounting cross-check against the compiled program's
        measured output+temp bytes."""
        import jax

        from deepspeed_tpu.runtime import memory_accounting as mem_acc

        def tree_bytes(tree):
            # the shared analytic primitive — one byte-pricing
            # implementation for both sides of the cross-check
            return sum(mem_acc.bytes_of(l.shape, l.dtype)
                       for l in jax.tree_util.tree_leaves(tree))

        C = self.num_chunks
        rng = jax.random.PRNGKey(0)
        scale = np.float32(1.0)
        x = self.module.input_fn(sample_micro)
        out, out_full = [], []
        for q in range(C):
            jits = self._stage_jits[q]
            # analytic transient bound per chunk: outputs (stash +
            # boundary activation/loss) + one argument-sized working set
            args_b = tree_bytes(self.stage_states[q].params) \
                + tree_bytes(x)
            with jax.set_mesh(self._chunk_mesh(q)):
                if q < C - 1:
                    x, _aux, stash = jax.eval_shape(
                        jits["fwd_stash"], self.stage_states[q].params,
                        x, rng)
                    extra = tree_bytes((x, _aux))
                else:
                    args_b += tree_bytes(sample_micro)
                    _loss, stash = jax.eval_shape(
                        jits["fwd_stash"], self.stage_states[q].params,
                        x, rng, sample_micro, scale)
                    extra = tree_bytes(_loss)
            out.append(tree_bytes(stash))
            out_full.append(out[-1] + extra + args_b)
        self._stash_out_bytes_per_chunk = out_full
        return out

    def _arm_stash(self, sample_micro):
        """Resolve zb-h1 activation stashing against its blockers.

        Sets self._stash_armed / self._stash_blockers /
        self._stash_bytes_per_chunk.  Armed, the executor runs the forward
        once per (chunk, micro) and the split backward consumes the stash;
        any blocker falls back to the remat split backward with DISARMED
        warnings naming it — including one warning PER STAGE whose
        analytic peak stash bytes exceed ``pipeline.stash_budget``."""
        from deepspeed_tpu.runtime.constants import (PIPELINE_STASH,
                                                     PIPELINE_STASH_BUDGET)
        from deepspeed_tpu.runtime.pipe import bubble_accounting as ba

        pcfg = self._config.pipeline
        requested = pcfg[PIPELINE_STASH]
        budget = int(pcfg[PIPELINE_STASH_BUDGET])
        self._stash_armed = False
        self._stash_blockers = []
        zb = self.pipe_schedule == sched_lib.SCHEDULE_ZB_H1
        if requested is False:
            return
        if not zb:
            if requested is True:
                # explicit request on a non-zb schedule warns; "auto" is
                # silently inert (stashing is a zb-h1 refinement)
                self._stash_blockers = [
                    f"effective schedule is '{self.pipe_schedule}' "
                    f"(stashing feeds the zb-h1 split backward; fused "
                    f"backwards already recompute exactly once)"]
                log_dist(
                    f"PipelineEngine: activation_stashing DISARMED — "
                    f"{self._stash_blockers[0]}",
                    ranks=[0], level=logging.WARNING)
            return
        blockers = []
        try:
            per_chunk = self._stash_bytes_estimate(sample_micro)
        except Exception as e:  # lint: allow-broad-except — stashing is an
            # optimization: any abstract-eval failure must DISARM it (and
            # name itself), never take down training
            per_chunk = None
            blockers.append(f"stash-size estimation failed "
                            f"({type(e).__name__}: {e})")
        self._stash_bytes_per_chunk = per_chunk
        if per_chunk is not None and budget > 0:
            rep = ba.simulate(sched_lib.compile_schedule(
                sched_lib.SCHEDULE_ZB_H1, self.micro_batches,
                self.num_stages, stash=True))
            for s, peak in enumerate(rep["peak_live_stash"]):
                need = peak * per_chunk[s]
                if need > budget:
                    why = (f"stage {s} needs {need} stash bytes at peak "
                           f"({peak} live micros x {per_chunk[s]} B) > "
                           f"pipeline.stash_budget={budget}")
                    blockers.append(why)
                    log_dist(
                        f"PipelineEngine: activation_stashing DISARMED on "
                        f"stage {s} — {why}; falling back to remat",
                        ranks=[0], level=logging.WARNING)
        self._stash_blockers = blockers
        self._stash_armed = not blockers
        if self._stash_armed and self._memacct is not None \
                and per_chunk is not None:
            # analytic-vs-measured cross-check (ISSUE 15): the same
            # residual estimate the stash_budget gate was sized from,
            # checked at report time against the compiled fwd_stash's
            # measured output+temp bytes — a >15% underestimate warns
            # that the budget under-provisions
            for q in range(self.num_chunks):
                self._memacct.expect(
                    f"chunk{q}:fwd_stash",
                    f"zb stash forward chunk {q}: vjp residuals "
                    f"({per_chunk[q]} B analytic, the stash_budget "
                    f"input) + boundary outputs",
                    self._stash_out_bytes_per_chunk[q],
                    field="transient_bytes")
        if self._stash_armed:
            import warnings

            # bwd_wgrad_stash's donated residuals that alias no output
            # draw XLA's 'donated buffers were not usable' warning at
            # lowering; that is the expected rendering of the stash
            # contract (buffer donors), not a lost alias.  Filter ONCE
            # here instead of paying a catch_warnings save/restore per
            # instruction in the dispatch hot loop.
            warnings.filterwarnings(
                "ignore", message="Some donated buffers were not usable")
        if blockers and not any("stash_budget" in b for b in blockers):
            log_dist(
                f"PipelineEngine: activation_stashing DISARMED — "
                f"{'; '.join(blockers)}; falling back to remat",
                ranks=[0], level=logging.WARNING)
        # the compiled stream depends on the stash decision (wgrad slots
        # are timed at d = w = 1 and stash slots are emitted)
        self._compiled_schedule = None

    # ------------------------------------------------------------------
    # batch placement
    # ------------------------------------------------------------------
    def _put_stage(self, tree, stage_id, batch_dims=1):
        """Place arrays on a stage submesh, dim0 sharded over 'data'."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        submesh = self._submeshes[stage_id]

        def put(x):
            x = np.asarray(x)
            spec = P(*(["data"] + [None] * (x.ndim - 1))) if x.ndim >= 1 else P()
            return jax.device_put(x, NamedSharding(submesh, spec))

        return jax.tree_util.tree_map(put, tree)

    def _transfer(self, arr, to_stage, edge=None, kind=None):
        """Move an activation/grad tensor to an adjacent stage's submesh —
        the p2p edge (reference pipe/p2p.py:31-58). ``edge``/``kind`` tag
        the chunk boundary for the p2p volume accounting (edge q = the
        boundary between global chunks q and q+1)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        if edge is not None:
            nbytes = int(arr.size) * arr.dtype.itemsize
            self._last_p2p_bytes += nbytes
            # first-seen payload per (edge, kind): the stable representative
            # for the analytic model (micros are shape-uniform slices of one
            # batch; see comm_accounting.pipe_p2p_bytes)
            self._p2p_edge_bytes.setdefault(edge, {}).setdefault(kind, nbytes)
        submesh = self._submeshes[to_stage]
        spec = P(*(["data"] + [None] * (arr.ndim - 1)))
        return jax.device_put(arr, NamedSharding(submesh, spec))

    # ------------------------------------------------------------------
    # schedule execution
    # ------------------------------------------------------------------
    def train_batch(self, data_iter=None, batch=None):
        """Run one full 1F1B-scheduled batch: gas micro-batches through all
        stages + optimizer step (reference pipe/engine.py:244-318)."""
        import jax

        micros = self._collect_micros(data_iter, batch)
        self._ensure_pipe_state(micros[0])
        if self._telemetry is not None:
            if self._mfu_n_params is None and self.stage_states is not None:
                self._mfu_n_params = sum(
                    int(l.size) for st in self.stage_states
                    for l in jax.tree_util.tree_leaves(st.params))
            self._note_mfu_workload(micros[0],
                                    micros_in_batch=self.micro_batches)
        self.tput_timer.start()

        losses, mid_auxes = self._exec_train_schedule(micros)
        self._chaos_poison_accum()

        # --- optimizer step (host-coordinated across stages) -----------
        tr = self._tracer
        _t0 = tr.begin() if tr is not None else 0.0
        lr = self._advance_lr()
        sq_total, all_finite = 0.0, True
        stats = []
        for s in range(self.num_chunks):
            with jax.set_mesh(self._chunk_mesh(s)):
                stats.append(self._stage_jits[s]["sqnorm"](
                    self.stage_states[s].accum))
        # one batched fetch for all chunks: a device_get per chunk would
        # serialize host<->device once per loop turn (graftlint host-sync)
        for sq, finite in jax.device_get(stats):
            sq_total += float(sq)
            all_finite &= bool(finite)

        scale = self._pipe_scaler.cur_scale
        if all_finite:
            # accum holds sum of scaled per-micro grads (each already /gas)
            inv_scale = 1.0 / scale
            gnorm = np.sqrt(sq_total) * inv_scale
            clip = self.gradient_clipping()
            clip_factor = min(1.0, clip / (gnorm + 1e-6)) if clip else 1.0
            for s in range(self.num_chunks):
                with jax.set_mesh(self._chunk_mesh(s)):
                    self.stage_states[s] = self._stage_jits[s]["apply_step"](
                        self.stage_states[s], np.float32(lr),
                        np.float32(inv_scale), np.float32(clip_factor))
            self._last_grad_norm = gnorm
        else:
            # overflow: drop grads; the shared scaler applies hysteresis
            self._host_skipped += 1
        self._pipe_scaler.update_scale(not all_finite)
        if not all_finite:
            log_dist(f"PipelineEngine: OVERFLOW, skipping step "
                     f"{self.global_steps + 1}, scale -> "
                     f"{self._pipe_scaler.cur_scale:g}", ranks=[0])
            import jax.numpy as jnp

            for s in range(self.num_chunks):
                with jax.set_mesh(self._chunk_mesh(s)):
                    st = self.stage_states[s]
                    # zeros_like, NOT a*0.0: accum holds Inf/NaN here and
                    # inf*0 = NaN would poison every subsequent step
                    zero = jax.tree_util.tree_map(jnp.zeros_like, st.accum)
                    self.stage_states[s] = st._replace(accum=zero)

        self.global_steps += 1
        self.micro_steps += self.micro_batches
        if tr is not None:
            tr.complete("optimizer_step", self._lane_train, _t0,
                        a0=self.global_steps)
            if not all_finite:
                tr.instant("overflow_skip", self._lane_train,
                           a0=self.global_steps)
        self.tput_timer.stop()
        # one reduction + one transfer instead of gas scalar fetches
        with jax.set_mesh(self._chunk_mesh(self.num_chunks - 1)):
            loss = float(jax.device_get(
                self._stage_jits[-1]["mean_scalar"](losses)))
        # mid-chunk aux losses (MoE load balance) join the reported
        # objective so train_batch returns the same number regardless of
        # stage count (the last chunk's own aux is already inside `loss`).
        # Per-chunk reductions dispatch async; ONE fetch collects them all.
        aux_means = []
        for s, auxes in enumerate(mid_auxes):
            if auxes:
                with jax.set_mesh(self._chunk_mesh(s)):
                    aux_means.append(self._stage_jits[s]["mean_scalar"](auxes))
        if aux_means:
            loss += float(np.sum(jax.device_get(aux_means)))
        self._last_loss = loss
        self._last_metrics = {
            "overflow": not all_finite,
            "grad_norm": getattr(self, "_last_grad_norm", 0.0),
            "loss_scale": scale, "loss": loss,
            "pipe_schedule": self.pipe_schedule,
            "pipe_p2p_bytes_per_step": self._last_p2p_bytes}
        mon = self._integrity
        if mon is not None and mon.sentinels_armed:
            # sentinels ride the values this interpreter ALREADY holds
            # on host — the batched sqnorm fetch above and the one loss
            # reduction: zero new device syncs (update_ratio stays
            # None; per-stage apply jits have no delta-norm outputs)
            mon.observe_step(self.global_steps, loss=loss,
                             grad_norm=float(self._last_grad_norm)
                             if all_finite else None,
                             update_ratio=None, overflow=not all_finite)
        self._observe_step_outcome(loss=loss, overflow=not all_finite)
        if self.global_steps % self.steps_per_print() == 0:
            self._report_progress(self.global_steps)
        return loss

    def eval_batch(self, data_iter=None, batch=None):
        """Forward-only pipelined evaluation (reference pipe/engine.py:320)."""
        import jax

        micros = self._collect_micros(data_iter, batch)
        self._ensure_pipe_state(micros[0])
        C = self.num_chunks
        losses = []
        rng = jax.random.fold_in(self._pipe_rng, self.global_steps)
        # forward wavefront over model chunks (with interleaving the
        # activation hops back to stage 0 after each chunk group)
        for mb, micro in enumerate(micros):
            x = self._put_stage(self.module.input_fn(micro), 0)
            for q in range(C):
                jits = self._stage_jits[q]
                with jax.set_mesh(self._chunk_mesh(q)):
                    if q == C - 1:
                        batch_dev = self._put_stage(micro, self.num_stages - 1)
                        losses.append(jits["eval_loss"](
                            self.stage_states[q].params, x, rng, batch_dev))
                    else:
                        x = jits["eval_fwd"](self.stage_states[q].params, x, rng)
                        x = self._transfer(
                            x, self.grid.chunk_owner_stage(q + 1))
        # single batched fetch: per-loss device_get would sync once per micro
        out = float(np.mean(jax.device_get(losses)))
        if self._watchdog is not None:
            # eval between optimizer steps is progress, not a stalled step
            self._watchdog.heartbeat()
        return out

    def _collect_micros(self, data_iter, batch):
        gas = self.micro_batches
        if batch is not None:
            if isinstance(batch, dict):
                return [{k: v[i] for k, v in batch.items()} for i in range(gas)]
            return list(batch)
        assert data_iter is not None, "train_batch needs data_iter or batch"
        return [next(data_iter) for _ in range(gas)]

    def _ensure_compiled_schedule(self):
        if self._compiled_schedule is None:
            self._compiled_schedule = sched_lib.compile_schedule(
                self.pipe_schedule, self.micro_batches, self.num_stages,
                self.virtual_stages, stash=self._stash_armed)
        return self._compiled_schedule

    def _exec_train_schedule(self, micros):
        """Execute the compiled schedule's per-stage instruction streams
        with queue semantics (the single-controller analog of reference
        _exec_schedule, pipe/engine.py:1148-1161): stages advance round-
        robin one instruction at a time; a Recv blocks its stage until the
        matching Send ran. Device programs still overlap — dispatch is
        async, ordering here is host-side only. A stream set that can
        never unblock raises instead of hanging."""
        import jax

        compiled = self._ensure_compiled_schedule()
        S = self.num_stages
        C = self.num_chunks
        streams = compiled.streams
        nbuf = compiled.num_buffers

        # per-CHUNK buffer slots
        in_act = [[None] * nbuf[q] for q in range(C)]    # fwd input (saved)
        out_act = [[None] * nbuf[q] for q in range(C)]   # fwd output
        in_grad = [[None] * nbuf[q] for q in range(C)]   # recv'd dL/dout
        out_grad = [[None] * nbuf[q] for q in range(C)]  # computed dL/din
        micro_dev = [[None] * nbuf[q] for q in range(C)] # loaded micro
        # the COMPILED stream is the single source of truth: stash mode
        # only runs against a stream that emitted stash slots
        stashed = compiled.stash
        # stash slots (zb-h1 stashing): the forward's vjp residuals, live
        # from ForwardPass until BackwardWeightPass donates them away
        stash_buf = [[None] * n for n in compiled.num_stash_slots]
        act_q = [deque() for _ in range(C)]   # inbound acts per dest chunk
        grad_q = [deque() for _ in range(C)]  # inbound grads per dest chunk
        losses = []
        mid_auxes = [[] for _ in range(C)]    # per-micro aux, mid chunks
        base_rng = jax.random.fold_in(self._pipe_rng, self.global_steps)
        micro_rngs = [jax.random.fold_in(base_rng, i)
                      for i in range(self.micro_batches)]
        scale = np.float32(self._pipe_scaler.cur_scale)
        self._last_p2p_bytes = 0
        # telemetry: one lane per PHYSICAL stage, one span per executed
        # compiled instruction (chunk/micro in the args) — the exported
        # trace renders the schedule, and bubble_accounting.replay_trace
        # replays exactly these spans for the measured-vs-analytic
        # cross-check.  The batch-begin marker scopes a replay to the
        # LAST batch (streams of two batches would pipeline across the
        # optimizer step the simulator doesn't model).
        tr = self._tracer
        if tr is not None:
            tr_lanes = [tr.lane(f"stage{s}") for s in range(S)]
            for n in ("LoadMicroBatch", "ForwardPass", "BackwardPass",
                      "BackwardGradPass", "BackwardWeightPass",
                      "SendActivation", "RecvActivation", "SendGrad",
                      "RecvGrad"):
                tr.intern(n, args=("chunk", "micro"))
            tr.instant("pipe_batch_begin", self._lane_train,
                       a0=self.global_steps)

        def chunk_of(cmd, s):
            return getattr(cmd, "chunk_id", 0) * S + s

        def exec_cmd(cmd, s):
            q = chunk_of(cmd, s)
            buf = cmd.buffer_id
            mb = cmd.micro_id
            jits = self._stage_jits[q]
            st = self.stage_states[q]
            if isinstance(cmd, sched_lib.SendActivation):
                dest = q + 1
                act_q[dest].append(self._transfer(
                    out_act[q][buf], self.grid.chunk_owner_stage(dest),
                    edge=q, kind="act"))
                out_act[q][buf] = None
            elif isinstance(cmd, sched_lib.SendGrad):
                dest = q - 1
                grad_q[dest].append(self._transfer(
                    out_grad[q][buf], self.grid.chunk_owner_stage(dest),
                    edge=q - 1, kind="grad"))
                out_grad[q][buf] = None
            elif isinstance(cmd, sched_lib.LoadMicroBatch):
                micro = micros[mb]
                if q == 0:
                    in_act[q][buf] = self._put_stage(
                        self.module.input_fn(micro), 0)
                if q == C - 1:
                    micro_dev[q][buf] = self._put_stage(micro, S - 1)
            elif isinstance(cmd, sched_lib.RecvActivation):
                in_act[q][buf] = act_q[q].popleft()
            elif isinstance(cmd, sched_lib.RecvGrad):
                in_grad[q][buf] = grad_q[q].popleft()
            elif isinstance(cmd, sched_lib.ForwardPass):
                with jax.set_mesh(self._chunk_mesh(q)):
                    if stashed:
                        # forward runs ONCE: its vjp residuals are the
                        # stash; the saved input (and last-chunk labels)
                        # free here — the residuals supersede them
                        if q == C - 1:
                            loss, stash_buf[q][buf] = jits["fwd_stash"](
                                st.params, in_act[q][buf], micro_rngs[mb],
                                micro_dev[q][buf], scale)
                            losses.append(loss)
                            micro_dev[q][buf] = None
                        else:
                            out_act[q][buf], aux, stash_buf[q][buf] = \
                                jits["fwd_stash"](st.params, in_act[q][buf],
                                                  micro_rngs[mb])
                            if self._module_has_aux:
                                mid_auxes[q].append(aux)
                        in_act[q][buf] = None
                    elif q < C - 1:
                        out_act[q][buf] = jits["fwd"](
                            st.params, in_act[q][buf], micro_rngs[mb])
                    # last chunk w/o stash: loss computed in the backward
            elif isinstance(cmd, sched_lib.BackwardPass):
                with jax.set_mesh(self._chunk_mesh(q)):
                    if q == C - 1:
                        new_accum, gx, loss = jits["bwd_last"](
                            st.params, st.accum, in_act[q][buf],
                            micro_rngs[mb], micro_dev[q][buf], scale)
                        losses.append(loss)
                        micro_dev[q][buf] = None
                    else:
                        new_accum, gx, aux = jits["bwd_mid"](
                            st.params, st.accum, in_act[q][buf],
                            micro_rngs[mb], in_grad[q][buf], scale)
                        if self._module_has_aux:
                            mid_auxes[q].append(aux)
                    self.stage_states[q] = st._replace(accum=new_accum)
                    out_grad[q][buf] = gx
                in_act[q][buf] = None
                in_grad[q][buf] = None
            elif isinstance(cmd, sched_lib.BackwardGradPass):
                # zb dgrad: unblocks the upstream stage.  Stashed: consume
                # the forward's residuals (no recompute), keeping the stash
                # and in_grad LIVE for the deferred wgrad.  Remat: keeps
                # in_act and in_grad live and re-runs the forward.
                with jax.set_mesh(self._chunk_mesh(q)):
                    if stashed:
                        if q == C - 1:
                            gx = jits["bwd_dgrad_stash"](stash_buf[q][buf])
                        else:
                            gx = jits["bwd_dgrad_stash"](
                                stash_buf[q][buf], in_grad[q][buf], scale)
                    elif q == C - 1:
                        gx, loss = jits["bwd_dgrad"](
                            st.params, in_act[q][buf], micro_rngs[mb],
                            micro_dev[q][buf], scale)
                        losses.append(loss)
                    else:
                        gx, aux = jits["bwd_dgrad"](
                            st.params, in_act[q][buf], micro_rngs[mb],
                            in_grad[q][buf], scale)
                        if self._module_has_aux:
                            mid_auxes[q].append(aux)
                    out_grad[q][buf] = gx
            elif isinstance(cmd, sched_lib.BackwardWeightPass):
                with jax.set_mesh(self._chunk_mesh(q)):
                    if stashed:
                        # the wgrad jit DONATES the stash (+ accum): the
                        # residual buffers free in place here (XLA's
                        # unusable-donation warning for donor-only leaves
                        # is filtered once at _arm_stash time)
                        if q == C - 1:
                            new_accum = jits["bwd_wgrad_stash"](
                                stash_buf[q][buf], st.accum)
                        else:
                            new_accum = jits["bwd_wgrad_stash"](
                                stash_buf[q][buf], st.accum,
                                in_grad[q][buf], scale)
                        stash_buf[q][buf] = None
                    elif q == C - 1:
                        new_accum = jits["bwd_wgrad"](
                            st.params, st.accum, in_act[q][buf],
                            micro_rngs[mb], micro_dev[q][buf], scale)
                        micro_dev[q][buf] = None
                    else:
                        new_accum = jits["bwd_wgrad"](
                            st.params, st.accum, in_act[q][buf],
                            micro_rngs[mb], in_grad[q][buf], scale)
                    self.stage_states[q] = st._replace(accum=new_accum)
                in_act[q][buf] = None
                in_grad[q][buf] = None
            else:  # pragma: no cover
                raise AssertionError(f"unknown instruction {cmd}")

        pc = [0] * S
        while True:
            progressed, alldone = False, True
            for s in range(S):
                if pc[s] >= len(streams[s]):
                    continue
                alldone = False
                cmd = streams[s][pc[s]]
                if isinstance(cmd, sched_lib.RecvActivation) and \
                        not act_q[chunk_of(cmd, s)]:
                    continue                    # blocked on the producer
                if isinstance(cmd, sched_lib.RecvGrad) and \
                        not grad_q[chunk_of(cmd, s)]:
                    continue
                if tr is None:
                    exec_cmd(cmd, s)
                else:
                    _t0 = tr.begin()
                    exec_cmd(cmd, s)
                    tr.complete(cmd.name, tr_lanes[s], _t0,
                                a0=getattr(cmd, "chunk_id", 0),
                                a1=getattr(cmd, "micro_id", -1))
                pc[s] += 1
                progressed = True
            if alldone:
                break
            if not progressed:  # pragma: no cover - compiler-verified
                blocked = [s for s in range(S) if pc[s] < len(streams[s])]
                raise RuntimeError(
                    f"pipeline schedule '{compiled.name}' deadlocked; "
                    f"stages {blocked} blocked at "
                    f"{[streams[s][pc[s]] for s in blocked]}")
        self._reduce_tied_grads()
        return losses, mid_auxes

    def _reduce_tied_grads(self):
        """Sum tied-param grad accumulators across tie-group stages and
        redistribute so each member applies the identical update. Stays on
        device: peers' accum shards transfer over ICI (device_put to the
        target submesh) and sum inside a jitted add — no host round-trip."""
        import jax

        groups = self.module.tied_groups(self.num_chunks)
        for key, stages in groups.items():
            pkey = f"tied_{key}"
            # snapshot pre-reduction accums: summing in place would make
            # later targets double-count already-reduced members
            originals = {s: self.stage_states[s].accum[pkey] for s in stages}
            for target in stages:
                total = originals[target]
                with jax.set_mesh(self._chunk_mesh(target)):
                    for s in stages:
                        if s == target:
                            continue
                        peer = jax.tree_util.tree_map(
                            lambda l, ref: jax.device_put(l, ref.sharding),
                            originals[s], total)
                        total = jax.tree_util.tree_map(
                            lambda a, b: a + b, total, peer)
                accum = dict(self.stage_states[target].accum)
                accum[pkey] = total
                self.stage_states[target] = \
                    self.stage_states[target]._replace(accum=accum)

    # ------------------------------------------------------------------
    # analytic schedule/bubble reporting
    # ------------------------------------------------------------------
    def pipeline_report(self, costs=None):
        """Analytic pipeline execution report for the ACTIVE schedule: the
        tick simulation's per-stage idle fractions, aggregate bubble
        fraction, peak live activation buffers (bubble_accounting), the
        1f1b baseline for comparison, and the p2p transfer volume
        (measured bytes from the last train_batch; per-boundary payloads
        once one batch has run). Deterministic on CPU — no device work."""
        from deepspeed_tpu.runtime import comm_accounting as ca
        from deepspeed_tpu.runtime.pipe import bubble_accounting as ba

        from deepspeed_tpu.runtime.constants import (PIPELINE_STASH,
                                                     PIPELINE_STASH_BUDGET)

        compiled = self._ensure_compiled_schedule()
        report = ba.simulate(compiled, costs)
        report["requested_schedule"] = self.requested_schedule
        report["schedule_blockers"] = list(self._schedule_blockers)
        budget = int(self._config.pipeline[PIPELINE_STASH_BUDGET])
        stash_info = {
            "requested": self._config.pipeline[PIPELINE_STASH],
            "armed": self._stash_armed,
            "blockers": list(self._stash_blockers),
            "budget_bytes": budget or None,
            # arming needs shapes: before the first batch the decision is
            # still open and the report says so instead of guessing
            "resolved": self.stage_states is not None,
        }
        if self._stash_bytes_per_chunk is not None:
            stash_info["bytes_per_micro_per_chunk"] = \
                list(self._stash_bytes_per_chunk)
            if self._stash_armed:
                stash_info["peak_bytes_per_stage"] = [
                    peak * self._stash_bytes_per_chunk[s]
                    for s, peak in enumerate(report["peak_live_stash"])]
        report["stash"] = stash_info
        if self.pipe_schedule != sched_lib.SCHEDULE_1F1B:
            base = ba.bubble_report(
                sched_lib.SCHEDULE_1F1B, self.micro_batches,
                self.num_stages, costs=costs)
            report["baseline_1f1b_bubble_fraction"] = \
                base["bubble_fraction"]
        p2p = {"measured_bytes_per_step": self._last_p2p_bytes or None}
        if self._p2p_edge_bytes:
            # model the recorded per-boundary payloads as budgeted
            # collectives (comm_accounting idiom; joins comm_budgets.json
            # via tools/comm_budget.py's canonical configs)
            acts = [b.get("act", 0) for _, b in
                    sorted(self._p2p_edge_bytes.items())]
            grads = [b.get("grad", 0) for _, b in
                     sorted(self._p2p_edge_bytes.items())]
            p2p["analytic_bytes_per_step"] = ca.pipe_p2p_bytes(
                act_bytes_per_edge=acts, grad_bytes_per_edge=grads,
                micro_batches=self.micro_batches)
        report["p2p"] = p2p
        return report

    def measured_bubble_report(self, costs=None):
        """Measured-vs-analytic bubble cross-check from the telemetry
        trace (None when tracing is disarmed; raises before the first
        traced train_batch).

        ``analytic`` simulates the compiled plan; ``measured`` replays
        the instruction spans the interpreter actually recorded for the
        LAST batch through the same simulator
        (bubble_accounting.replay_trace) — faithful execution reproduces
        the analytic per-stage idle fractions exactly, and
        ``max_abs_idle_error`` is the tier-1-pinned drift bound.
        ``wall_clock`` is the honest wall-time lane utilization of the
        same spans (dispatch-bound on a CPU mesh; the transferable claim
        is the replay)."""
        from deepspeed_tpu.runtime.pipe import bubble_accounting as ba
        from deepspeed_tpu.telemetry import lane_utilization

        tr = self._tracer
        if tr is None:
            return None
        if tr.dropped:
            raise ValueError(
                f"telemetry trace ring dropped {tr.dropped} events — the "
                f"instruction stream is holey and a replay would wedge; "
                f"raise telemetry.trace_capacity (now {tr.capacity})")
        events = tr.events()
        # scope to the LAST batch: streams spanning two batches would
        # pipeline across the optimizer step the simulator doesn't model
        last_begin = 0
        for i, ev in enumerate(events):
            if ev["name"] == "pipe_batch_begin":
                last_begin = i
        events = events[last_begin:]
        compiled = self._ensure_compiled_schedule()
        measured = ba.replay_trace(events, compiled, costs)
        analytic = ba.simulate(compiled, costs)
        lanes = {f"stage{s}" for s in range(self.num_stages)}
        return {
            "analytic": analytic,
            "measured": measured,
            "wall_clock": lane_utilization(events, lanes=lanes),
            "max_abs_idle_error": max(
                abs(m - a) for m, a in zip(measured["idle_fraction"],
                                           analytic["idle_fraction"])),
        }

    def _analytic_memory_components(self):
        """Pipeline analytic memory: per-STAGE component bytes (each
        stage is a separate submesh, so the watermark that matters is
        the worst stage, not a sum across them), chunk states aggregated
        onto their owner stages, plus the ZB stash residual peak per
        stage when stashing is armed.  None before the first batch."""
        if self.stage_states is None:
            return None
        from deepspeed_tpu.runtime import memory_accounting as mem_acc
        from deepspeed_tpu.runtime.pipe import bubble_accounting as ba

        S = self.num_stages
        per_stage = [{"params_bytes": 0, "master_bytes": 0,
                      "optimizer_state_bytes": 0, "grad_accum_bytes": 0}
                     for _ in range(S)]
        for q, st in enumerate(self.stage_states):
            s = self.grid.chunk_owner_stage(q)
            per_stage[s]["params_bytes"] += \
                mem_acc.tree_device_bytes(st.params)
            per_stage[s]["master_bytes"] += \
                mem_acc.tree_device_bytes(st.master)
            per_stage[s]["optimizer_state_bytes"] += \
                mem_acc.tree_device_bytes(st.opt_state)
            per_stage[s]["grad_accum_bytes"] += \
                mem_acc.tree_device_bytes(st.accum)
        stash_peak = [0] * S
        if self._stash_armed and self._stash_bytes_per_chunk is not None:
            rep = ba.simulate(self._ensure_compiled_schedule())
            for s, peak in enumerate(rep["peak_live_stash"]):
                stash_peak[s] = peak * self._stash_bytes_per_chunk[s]
        stages = []
        for s in range(S):
            persistent = sum(per_stage[s].values())
            stages.append({
                "components": per_stage[s],
                "transient": {"stash_bytes": stash_peak[s]},
                "persistent_bytes": persistent,
                "peak_bytes": persistent + stash_peak[s],
            })
        worst = max(range(S), key=lambda s: stages[s]["peak_bytes"])
        return {
            "per_stage": stages,
            "persistent_bytes": stages[worst]["persistent_bytes"],
            "transient_bytes": stages[worst]["transient"]["stash_bytes"],
            # devices are per stage: the fleet watermark is the worst
            # stage's peak, not the sum over submeshes
            "peak_bytes": stages[worst]["peak_bytes"],
            "worst_stage": worst,
        }

    def telemetry_report(self):
        """Base unified report plus the pipeline sections: the analytic
        ``pipeline_report()`` and — once a traced batch has run — the
        measured-vs-analytic bubble cross-check."""
        report = super().telemetry_report()
        report["pipeline"] = self.pipeline_report()
        tr = self._tracer
        if tr is not None and not tr.dropped \
                and any(e["name"] == "pipe_batch_begin"
                        for e in tr.events()):
            report["pipeline"]["measured"] = self.measured_bubble_report()
        return report

    # ------------------------------------------------------------------
    # checkpointing (pipeline layout: per-stage state files)
    # ------------------------------------------------------------------
    def _layer_key_set(self):
        """Stage-count-independent universe of layer param keys: layer-
        granular files are keyed by these, so a checkpoint written at pp=N
        can be read at pp=M (reference pipe/module.py:536-567 writes
        layer_XX-model_states files for the same reason)."""
        return {layer.param_key for layer in self.module._layers
                if layer.param_key is not None}

    @staticmethod
    def _path_layer_key(path, layer_keys):
        import jax

        for p in path:
            if isinstance(p, jax.tree_util.DictKey) and str(p.key) in layer_keys:
                return str(p.key)
        return None

    def _stage_save_tree(self, st):
        """The persisted slice of a StageState. accum is excluded: steps only
        complete at accumulation boundaries, where it is zeros."""
        return {"params": st.params, "master": st.master,
                "opt_state": st.opt_state}

    def _chaos_poison_accum(self):
        """Pipeline variant of the chaos NaN-grad hook: the accumulator
        lives per stage, not on a single TrainState."""
        from deepspeed_tpu.runtime.resilience import chaos

        if chaos.active() is None or not chaos.consume_nan_grad_step():
            return
        import jax
        import jax.numpy as jnp

        for s in range(self.num_chunks):
            with jax.set_mesh(self._chunk_mesh(s)):
                st = self.stage_states[s]
                poisoned = jax.tree_util.tree_map(
                    lambda a: jnp.full_like(a, jnp.nan), st.accum)
                self.stage_states[s] = st._replace(accum=poisoned)

    def _assert_saveable(self):
        assert self.stage_states is not None, "no pipeline state to save"

    def _assert_loadable(self):
        assert self.stage_states is not None, \
            "run one batch (or _ensure_pipe_state) before load_checkpoint"

    def _resolve_ckpt_backend(self, backend):
        if backend not in (None, "auto", "npz", "npz-layer"):
            raise ValueError(
                f"pipeline checkpoints only support the layer-granular npz "
                f"backend; got backend={backend!r}")
        return "npz-layer"

    def _ckpt_host_snapshot(self, client_state, backend, copy_host=False):
        """Device->host transfer of every stage's persisted slice, plus
        the metadata — the foreground part of a commit; the writer below
        is pure filesystem work over this snapshot.  ``copy_host`` is
        moot here: device_get already yields host arrays owned by the
        snapshot (nothing mutates them in place)."""
        import jax

        from deepspeed_tpu.runtime.resilience import reshard

        host_states = [jax.device_get(self._stage_save_tree(st))
                       for st in self.stage_states]
        meta = {
            "global_steps": self.global_steps,
            "micro_steps": self.micro_steps,
            "skipped_steps": self._host_skipped,
            "cur_scale": self._pipe_scaler.cur_scale,
            "scaler_state": self._pipe_scaler.__dict__.copy(),
            "num_stages": self.num_stages,
            "virtual_stages": self.virtual_stages,
            "schedule": self.pipe_schedule,
            "partition": self.module.partition_layers(self.num_chunks),
            "layer_keys": sorted(self._layer_key_set()),
            "format": "layer-granular",
            "lr_scheduler": self.lr_scheduler.state_dict()
            if self.lr_scheduler is not None else None,
            "client_state": client_state,
            "dp_world_size": self.dp_world_size,
            reshard.TOPOLOGY_KEY: reshard.topology_manifest(self),
            reshard.DATA_POSITION_KEY: reshard.data_position(self),
        }
        return {"host_states": host_states, "meta": meta,
                "backend": "npz-layer"}

    def _write_snapshot_files(self, path, snap):
        """Pipeline payload: layer-granular layout — one file per layer
        param key, entries keyed by the leaf's tree path (identical no
        matter which stage owns the layer), plus a 'globals' file for
        layer-independent optimizer scalars (identical on every stage).
        Runs inside the atomic commit path (sync, or on the async commit
        thread): ``path`` is the tag temp dir and each write feeds the
        chaos fault-injection hooks."""
        import jax

        from deepspeed_tpu.runtime.checkpoint_utils import named_leaf_entry
        from deepspeed_tpu.runtime.resilience import chaos

        layer_keys = set(snap["meta"]["layer_keys"])
        per_layer = {}
        global_leaves = {}
        for host in snap["host_states"]:
            for p, leaf in jax.tree_util.tree_flatten_with_path(host)[0]:
                entry = named_leaf_entry(jax.tree_util.keystr(p), leaf)
                k = self._path_layer_key(p, layer_keys)
                if k is None:
                    global_leaves.update(entry)
                else:
                    per_layer.setdefault(k, {}).update(entry)
        for k, entries in per_layer.items():
            fname = os.path.join(path, f"{k}-states.npz")
            self._ckpt_savez(fname, **entries)
            chaos.file_written(fname)
        fname = os.path.join(path, "globals-states.npz")
        self._ckpt_savez(fname, **global_leaves)
        chaos.file_written(fname)
        fname = os.path.join(path, "metadata.pkl")
        with open(fname, "wb") as f:
            pickle.dump(snap["meta"], f)
        chaos.file_written(fname)
        log_dist(f"Wrote pipeline checkpoint payload "
                 f"({len(per_layer)} layer files)", ranks=[0])

    def _write_checkpoint_files(self, path, client_state, backend):
        backend = self._resolve_ckpt_backend(backend)
        self._write_snapshot_files(
            path, self._ckpt_host_snapshot(client_state, backend))
        return backend

    def _ckpt_state_snapshot(self):
        snap = super()._ckpt_state_snapshot()
        snap["stage_states"] = list(self.stage_states) \
            if self.stage_states is not None else None
        snap["pipe_scaler"] = dict(self._pipe_scaler.__dict__) \
            if getattr(self, "_pipe_scaler", None) is not None else None
        return snap

    def _ckpt_state_restore(self, snap):
        super()._ckpt_state_restore(snap)
        if snap.get("stage_states") is not None:
            self.stage_states = snap["stage_states"]
        if snap.get("pipe_scaler") is not None:
            self._pipe_scaler.__dict__.update(snap["pipe_scaler"])

    def _load_checkpoint_tag(self, load_dir, tag, load_module_strict=True,
                             load_optimizer_states=True,
                             load_lr_scheduler_states=True, elastic=False):
        import jax

        path = os.path.join(load_dir, str(tag))
        with open(os.path.join(path, "metadata.pkl"), "rb") as f:
            meta = pickle.load(f)
        assert meta.get("format") == "layer-granular", \
            "pre-round-4 per-stage pipeline checkpoints are not readable; " \
            "re-save with this version"
        assert self.stage_states is not None, \
            "run one batch (or _ensure_pipe_state) before load_checkpoint"
        layer_keys = self._layer_key_set()
        saved_keys = set(meta.get("layer_keys", []))
        if load_module_strict:
            assert saved_keys == layer_keys, \
                (f"checkpoint layers {sorted(saved_keys)} != module layers "
                 f"{sorted(layer_keys)}")

        from deepspeed_tpu.runtime.checkpoint_utils import named_leaf_lookup

        files = {}

        def lookup(k, name):
            fname = "globals-states.npz" if k is None else f"{k}-states.npz"
            if fname not in files:
                files[fname] = np.load(os.path.join(path, fname))
            return named_leaf_lookup(files[fname], name)

        # rebuild each (possibly re-partitioned) stage from the layer files:
        # every leaf of the fresh stage state is looked up by (layer key,
        # tree path), which is stage-layout independent
        new_states = []
        for st in self.stage_states:
            tpl = jax.device_get(self._stage_save_tree(st))
            leaves, treedef = jax.tree_util.tree_flatten_with_path(tpl)
            restored = [lookup(self._path_layer_key(p, layer_keys),
                               jax.tree_util.keystr(p))
                        for p, _ in leaves]
            host = jax.tree_util.tree_unflatten(treedef, restored)
            ref = self._stage_save_tree(st)
            dev = jax.tree_util.tree_map(
                lambda l, r: jax.device_put(l, r.sharding), host, ref)
            new_states.append(st._replace(
                params=dev["params"], master=dev["master"],
                opt_state=dev["opt_state"]))
        self.stage_states = new_states
        self.global_steps = meta["global_steps"]
        self.micro_steps = meta["micro_steps"]
        self._host_skipped = meta["skipped_steps"]
        self._pipe_scaler.cur_scale = meta["cur_scale"]
        for k, v in meta.get("scaler_state", {}).items():
            setattr(self._pipe_scaler, k, v)
        if load_lr_scheduler_states and self.lr_scheduler is not None \
                and meta.get("lr_scheduler") is not None:
            self.lr_scheduler.load_state_dict(meta["lr_scheduler"])
        log_dist(f"Loaded pipeline checkpoint {path} (saved at "
                 f"{meta['num_stages']}x{meta.get('virtual_stages', 1)} "
                 f"chunks/{meta.get('schedule')}, now "
                 f"{self.num_stages}x{self.virtual_stages}/"
                 f"{self.pipe_schedule})", ranks=[0])
        return path, self._elastic_client_state(meta, elastic)
