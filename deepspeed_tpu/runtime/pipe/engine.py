"""PipelineEngine — pipeline-parallel training over stage submeshes.

Reference behavior: deepspeed/runtime/pipe/engine.py:45-1169 (instruction
dispatch `_exec_schedule` :1148, train_batch :244, eval_batch :320, p2p via
2-rank broadcast groups).

TPU-native architecture: the full device mesh (pipe, data, model) is split
into one submesh per stage; each stage's params/optimizer state live only on
its submesh (pipeline memory scaling), with ZeRO sharding over the submesh's
'data' axis on top. The engine executes the SAME declarative instruction
schedules as the reference (runtime/pipe/schedule.py), but:

- SendActivation/RecvActivation/SendGrad/RecvGrad are `jax.device_put`
  transfers between adjacent submeshes (ICI neighbor copies — the analog of
  the reference's broadcast-pair p2p, pipe/p2p.py:31-58);
- ForwardPass/BackwardPass are per-stage jitted calls; the single-controller
  runtime dispatches them asynchronously, so stages on disjoint devices
  overlap exactly as the 1F1B schedule intends;
- BackwardPass recomputes the stage forward inside the jit (vjp-with-remat) —
  activation checkpointing per stage, matching the reference's
  activation-checkpoint-every-stage default;
- ReduceGrads is implicit: XLA inserts the data-axis psum inside the
  backward jit (the reference's bucketed allreduce, engine.py:852-868);
- ReduceTiedGrads sums accumulated tied-param grads across the stages in the
  tie group and redistributes, so identical optimizer updates keep tied
  copies in sync (reference module.py:405-418).

fp16 dynamic loss scaling runs host-side here (the schedule is host-driven
anyway): per-stage finite checks combine on host, overflow skips the step
and halves the scale (reference fp16/loss_scaler.py:79-170 semantics).
"""
import os
import pickle
from collections import deque
from typing import NamedTuple

import numpy as np

from deepspeed_tpu.parallel import mesh as mesh_lib
from deepspeed_tpu.runtime.engine import DeepSpeedEngine
from deepspeed_tpu.runtime.pipe import schedule as sched_lib
from deepspeed_tpu.runtime.pipe.module import PipelineModule
from deepspeed_tpu.runtime.pipe.topology import (PipelineParallelGrid,
                                                 PipeModelDataParallelTopology)
from deepspeed_tpu.utils.logging import log_dist, logger


class StageState(NamedTuple):
    params: object      # compute-dtype params for this stage's layers
    master: object      # fp32 master (None in fp32 mode)
    opt_state: object   # optimizer state over master
    accum: object       # fp32 grad accumulator


class PipelineEngine(DeepSpeedEngine):
    """Training engine for PipelineModule models. Use train_batch/eval_batch;
    forward/backward/step are disabled (reference pipe/engine.py:1090-1098)."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        assert isinstance(self.module, PipelineModule), \
            "PipelineEngine requires a PipelineModule model"
        assert self.zero_optimization_stage() <= 2

        import jax

        self.num_stages = mesh_lib.pp_size(self.mesh)
        self.module.num_stages = self.num_stages
        self.micro_batches = self.gradient_accumulation_steps()

        topo = PipeModelDataParallelTopology(
            num_pp=self.num_stages, num_mp=self.mp_world_size,
            num_dp=self.dp_world_size)
        self.grid = PipelineParallelGrid(topology=topo, rank=0)

        # one submesh per stage: mesh.devices is (pipe, data, seq, model)
        self._submeshes = []
        for s in range(self.num_stages):
            self._submeshes.append(
                jax.sharding.Mesh(self.mesh.devices[s],
                                  ("data", "seq", "model")))

        self.stage_states = None          # list[StageState], lazy
        self._stage_shardings = None
        self._stage_jits = None
        # host-side loss scaling: the schedule is host-driven, so the shared
        # host DynamicLossScaler owns the policy (hysteresis, window, floor)
        if self.fp16_enabled():
            from deepspeed_tpu.runtime.fp16.loss_scaler import CreateLossScaler

            args_ls = dict(self._config.dynamic_loss_scale_args or {})
            args_ls.setdefault("init_scale",
                               self._config.initial_dynamic_scale)
            self._pipe_scaler = CreateLossScaler(
                static_loss_scale=self._config.loss_scale or 0,
                dynamic_scale_args=args_ls)
        else:
            from deepspeed_tpu.runtime.fp16.loss_scaler import LossScaler

            self._pipe_scaler = LossScaler(scale=1)
        self._host_skipped = 0

        log_dist(
            f"PipelineEngine: stages={self.num_stages} "
            f"micro_batches={self.micro_batches} dp={self.dp_world_size} "
            f"mp={self.mp_world_size}", ranks=[0])

    # ------------------------------------------------------------------
    # disabled base API (reference pipe/engine.py:1090-1098)
    # ------------------------------------------------------------------
    def forward(self, *a, **k):
        raise RuntimeError("PipelineEngine: use train_batch()/eval_batch()")

    def backward(self, *a, **k):
        raise RuntimeError("PipelineEngine: use train_batch()/eval_batch()")

    def step(self, *a, **k):
        raise RuntimeError("PipelineEngine: use train_batch()/eval_batch()")

    @property
    def skipped_steps(self):
        return self._host_skipped

    def loss_scale(self):
        return self._pipe_scaler.cur_scale

    def is_first_stage(self):
        """True: the single controller owns every stage, including stage 0
        (reference semantics — 'does this rank host the first stage' — are
        per-rank; here one process IS all ranks, so both predicates hold
        and first/last-stage-only work like data loading and loss handling
        runs on this process)."""
        return True

    def is_last_stage(self):
        return True

    # ------------------------------------------------------------------
    # state construction
    # ------------------------------------------------------------------
    def _stage_zero_shardings(self, submesh, params_template):
        """NamedShardings for one stage: params take the layers' TP specs
        over the submesh 'model' axis (PP x TP — the reference's 3D grid,
        pipe/topology.py:246-249), master/opt/accum additionally
        ZeRO-sharded over the submesh 'data' axis."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        stage = self.zero_optimization_stage()
        dp = submesh.shape["data"]

        tp_spec = self.module.param_partition_spec(params_template)
        is_p = lambda x: isinstance(x, P)  # noqa: E731
        param_sh = jax.tree_util.tree_map(
            lambda s: NamedSharding(submesh, s), tp_spec, is_leaf=is_p)
        if stage == 0:
            zero_spec = tp_spec
            zero = param_sh
        else:
            zero_spec = jax.tree_util.tree_map(
                lambda s, l: mesh_lib.zero_merge_spec(s, l, dp),
                tp_spec, params_template, is_leaf=is_p)
            zero = jax.tree_util.tree_map(
                lambda s: NamedSharding(submesh, s), zero_spec, is_leaf=is_p)

        # optimizer-state shardings (same policy as the base engine,
        # runtime/engine.py:_build_shardings): the optimizer declares its
        # state layout via state_spec; fallback matches param shapes
        rep = NamedSharding(submesh, P())
        opt_template = jax.eval_shape(self.optimizer.init_state,
                                      params_template)
        flat_opt, opt_def = jax.tree_util.tree_flatten(opt_template)
        if hasattr(self.optimizer, "state_spec"):
            spec_tree = self.optimizer.state_spec(zero_spec)
            spec_flat = jax.tree_util.tree_flatten(
                spec_tree, is_leaf=lambda x: x is None or isinstance(x, P))[0]
            assert len(spec_flat) == len(flat_opt)
            opt_sh_flat = [rep if s is None else NamedSharding(submesh, s)
                           for s in spec_flat]
        else:
            from deepspeed_tpu.runtime.utils import opt_shardings_by_shape

            zero_flat = jax.tree_util.tree_leaves(zero)
            shapes = [tuple(l.shape) for l in
                      jax.tree_util.tree_leaves(params_template)]
            opt_sh_flat = opt_shardings_by_shape(
                flat_opt, shapes, zero_flat, rep)
        opt_sh = opt_def.unflatten(opt_sh_flat)
        return param_sh, zero, opt_sh

    def _ensure_pipe_state(self, sample_micro):
        if self.stage_states is not None:
            return
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        # init full params on host once (layer by layer), then scatter each
        # stage's slice to its submesh
        init_rng, self._pipe_rng = jax.random.split(self._init_rng)
        # init on the HOST cpu backend: local_devices()[0] would be an
        # accelerator chip and the full fp32 model + a whole-model forward
        # would defeat per-stage memory scaling
        try:
            host_dev = jax.local_devices(backend="cpu")[0]
        except RuntimeError:  # pragma: no cover - cpu backend always exists
            host_dev = jax.local_devices()[0]
        with jax.default_device(host_dev):
            full_params = self.module.init(init_rng, sample_micro)
        full_params = jax.tree_util.tree_map(
            lambda l: np.asarray(jax.device_get(l), dtype=np.float32),
            full_params)
        parts = self.module.partition_layers(self.num_stages)
        logger.info(f"pipeline partition boundaries: {parts}")

        self.stage_states = []
        self._stage_shardings = []
        for s in range(self.num_stages):
            submesh = self._submeshes[s]
            keys = self.module.stage_param_keys(s)
            p32 = {k: full_params[k] for k in keys}
            rep, zero, opt_sh = self._stage_zero_shardings(submesh, p32)

            master = jax.tree_util.tree_map(
                lambda l, sh: jax.device_put(l, sh), p32, zero) \
                if self.mixed_precision else None
            params = jax.tree_util.tree_map(
                lambda l, sh: jax.device_put(
                    np.asarray(l, dtype=self.compute_dtype), sh), p32, rep)
            opt_src = master if self.mixed_precision else \
                jax.tree_util.tree_map(lambda l, sh: jax.device_put(l, sh),
                                       p32, zero)
            with jax.set_mesh(submesh):
                # out_shardings pins the declared layout — unconstrained,
                # XLA would pick its own and void the ZeRO partitioning
                opt_state = jax.jit(self.optimizer.init_state,
                                    out_shardings=opt_sh)(opt_src)
                accum = jax.tree_util.tree_map(
                    lambda l: jnp.zeros(l.shape, jnp.float32), p32)
                accum = jax.tree_util.tree_map(
                    lambda l, sh: jax.device_put(l, sh), accum, zero)
            self.stage_states.append(StageState(
                params=params, master=master, opt_state=opt_state,
                accum=accum))
            self._stage_shardings.append((rep, zero, opt_sh))
        self._build_stage_jits()
        n = sum(self.module.num_params(st.params) for st in self.stage_states)
        log_dist(f"Pipeline state initialized: {n/1e6:.1f}M params over "
                 f"{self.num_stages} stages", ranks=[0])

    def _build_stage_jits(self):
        import jax
        import jax.numpy as jnp

        module = self.module
        S = self.num_stages
        gas = self.micro_batches
        loss_fn = module.loss_fn
        # does any layer sow aux losses (MoE)? decided by module.init()
        self._module_has_aux = any(l.has_losses for l in module._layers)

        self._stage_jits = []
        for s in range(S):
            is_last = s == S - 1

            def fwd(params, x, rng, s=s):
                return module.forward_stage(params, x, s, rng, train=True)

            def fwd_aux(params, x, rng, s=s):
                # stage forward + stage-local sown aux losses (MoE load
                # balance): backward adds them to the objective directly
                return module.forward_stage(params, x, s, rng, train=True,
                                            return_aux=True)

            def fwd_loss(params, x, rng, batch, s=s):
                out, aux = module.forward_stage(params, x, s, rng,
                                                train=True, return_aux=True)
                loss, _ = loss_fn(out, batch)
                return loss, aux

            rep_sh, zero_sh, opt_sh = self._stage_shardings[s]

            def accum_add(accum, gp, zero_sh=zero_sh):
                # pin the ZeRO layout: without the constraint XLA is free to
                # re-lay-out the donated accumulator after the add
                return jax.tree_util.tree_map(
                    lambda a, g, sh: jax.lax.with_sharding_constraint(
                        a + g.astype(jnp.float32), sh),
                    accum, gp, zero_sh)

            # NOTE: closures bind loop-locals via default args — a bare
            # reference would late-bind to the LAST stage's function.
            # backward + gradient accumulation are ONE jit (donated accum):
            # the host-driven schedule pays one dispatch per BackwardPass
            # instead of two, and the grads never materialize outside the
            # accumulator.
            def bwd_last(params, accum, x, rng, batch, scale,
                         fwd_loss=fwd_loss, accum_add=accum_add):
                def scaled(params, x):
                    loss, aux = fwd_loss(params, x, rng, batch)
                    # reported loss includes the stage-local aux term so the
                    # two executors of a PipelineModule (this engine and the
                    # sequential base-engine path via module.loss) agree.
                    # Mid-stage aux terms enter gradients only — a truly
                    # global reported objective would need an extra host
                    # reduction per micro-batch.
                    with_aux = loss.astype(jnp.float32) + aux
                    return with_aux * scale / gas, with_aux

                # integer x (token ids reaching the last stage when pipe=1)
                # is not differentiable and its grad is never sent anywhere
                if jnp.issubdtype(jnp.asarray(x).dtype, jnp.inexact):
                    (_, loss), grads = jax.value_and_grad(
                        scaled, argnums=(0, 1), has_aux=True)(params, x)
                    gp, gx = grads
                else:
                    (_, loss), gp = jax.value_and_grad(
                        scaled, argnums=0, has_aux=True)(params, x)
                    gx = jnp.zeros((), jnp.float32)
                return accum_add(accum, gp), gx, loss

            def bwd_mid(params, accum, x, rng, gy, scale, fwd_aux=fwd_aux,
                        accum_add=accum_add):
                def f(p, x):
                    y, aux = fwd_aux(p, x, rng)
                    return y, jnp.asarray(aux, jnp.float32)

                (_, aux), vjp = jax.vjp(f, params, x)
                # aux cotangent scale/gas: the stage-local aux losses enter
                # the objective with the same loss scaling as the last
                # stage's loss term
                gp, gx = vjp((gy, (scale / gas).astype(jnp.float32)))
                # raw aux returned so train_batch can report the FULL
                # objective (last-stage loss + every stage's aux)
                return accum_add(accum, gp), gx, aux

            def sqnorm(accum):
                total = jnp.float32(0.0)
                finite = jnp.asarray(True)
                for g in jax.tree_util.tree_leaves(accum):
                    g32 = g.astype(jnp.float32)
                    total += jnp.sum(jnp.square(g32))
                    finite &= jnp.all(jnp.isfinite(g32))
                return total, finite

            optimizer = self.optimizer
            mixed = self.mixed_precision
            cdtype = self.compute_dtype

            def apply_step(state: StageState, lr, inv_scale, clip_factor,
                           rep_sh=rep_sh, zero_sh=zero_sh, opt_sh=opt_sh):
                grads = jax.tree_util.tree_map(
                    lambda g: g * inv_scale * clip_factor, state.accum)
                target = state.master if mixed else state.params
                new_master, new_opt = optimizer.update(
                    grads, state.opt_state, target, lr=lr)
                new_opt = jax.tree_util.tree_map(
                    jax.lax.with_sharding_constraint, new_opt, opt_sh)
                # pin layouts: params keep the TP spec (replicated over
                # 'data' — the ZeRO all-gather happens here, reference
                # stage2.py:1556-1590), master stays ZeRO-sharded.
                # Unconstrained, XLA would leave params data-sharded and
                # re-gather on every forward.
                if mixed:
                    new_master = jax.tree_util.tree_map(
                        jax.lax.with_sharding_constraint, new_master, zero_sh)
                    new_params = jax.tree_util.tree_map(
                        lambda l, sh: jax.lax.with_sharding_constraint(
                            l.astype(cdtype), sh), new_master, rep_sh)
                else:
                    new_params = jax.tree_util.tree_map(
                        jax.lax.with_sharding_constraint, new_master, rep_sh)
                    new_master = None
                zero_accum = jax.tree_util.tree_map(
                    lambda l, sh: jax.lax.with_sharding_constraint(
                        jnp.zeros_like(l), sh), state.accum, zero_sh)
                return StageState(params=new_params, master=new_master,
                                  opt_state=new_opt, accum=zero_accum)

            def eval_fwd(params, x, rng, s=s):
                return module.forward_stage(params, x, s, rng, train=False)

            def eval_loss(params, x, rng, batch, s=s):
                out = module.forward_stage(params, x, s, rng, train=False)
                loss, _ = loss_fn(out, batch)
                return loss

            submesh = self._submeshes[s]
            jits = {
                "fwd": jax.jit(fwd),
                "bwd_last": jax.jit(bwd_last, donate_argnums=(1,))
                if is_last else None,
                "bwd_mid": jax.jit(bwd_mid, donate_argnums=(1,)),
                "sqnorm": jax.jit(sqnorm),
                "apply_step": jax.jit(apply_step, donate_argnums=(0,)),
                "eval_fwd": jax.jit(eval_fwd),
                "eval_loss": jax.jit(eval_loss) if is_last else None,
                "mean_scalar": jax.jit(lambda ls: jnp.stack(ls).mean()),
                "mesh": submesh,
            }
            self._stage_jits.append(jits)

    # ------------------------------------------------------------------
    # batch placement
    # ------------------------------------------------------------------
    def _put_stage(self, tree, stage_id, batch_dims=1):
        """Place arrays on a stage submesh, dim0 sharded over 'data'."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        submesh = self._submeshes[stage_id]

        def put(x):
            x = np.asarray(x)
            spec = P(*(["data"] + [None] * (x.ndim - 1))) if x.ndim >= 1 else P()
            return jax.device_put(x, NamedSharding(submesh, spec))

        return jax.tree_util.tree_map(put, tree)

    def _transfer(self, arr, to_stage):
        """Move an activation/grad tensor to an adjacent stage's submesh —
        the p2p edge (reference pipe/p2p.py:31-58)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        submesh = self._submeshes[to_stage]
        spec = P(*(["data"] + [None] * (arr.ndim - 1)))
        return jax.device_put(arr, NamedSharding(submesh, spec))

    # ------------------------------------------------------------------
    # schedule execution
    # ------------------------------------------------------------------
    def train_batch(self, data_iter=None, batch=None):
        """Run one full 1F1B-scheduled batch: gas micro-batches through all
        stages + optimizer step (reference pipe/engine.py:244-318)."""
        import jax

        micros = self._collect_micros(data_iter, batch)
        self._ensure_pipe_state(micros[0])
        self.tput_timer.start()

        losses, mid_auxes = self._exec_train_schedule(micros)
        self._chaos_poison_accum()

        # --- optimizer step (host-coordinated across stages) -----------
        lr = self._advance_lr()
        sq_total, all_finite = 0.0, True
        stats = []
        for s in range(self.num_stages):
            with jax.set_mesh(self._submeshes[s]):
                stats.append(self._stage_jits[s]["sqnorm"](
                    self.stage_states[s].accum))
        for sq, finite in stats:
            sq_total += float(jax.device_get(sq))
            all_finite &= bool(jax.device_get(finite))

        scale = self._pipe_scaler.cur_scale
        if all_finite:
            # accum holds sum of scaled per-micro grads (each already /gas)
            inv_scale = 1.0 / scale
            gnorm = np.sqrt(sq_total) * inv_scale
            clip = self.gradient_clipping()
            clip_factor = min(1.0, clip / (gnorm + 1e-6)) if clip else 1.0
            for s in range(self.num_stages):
                with jax.set_mesh(self._submeshes[s]):
                    self.stage_states[s] = self._stage_jits[s]["apply_step"](
                        self.stage_states[s], np.float32(lr),
                        np.float32(inv_scale), np.float32(clip_factor))
            self._last_grad_norm = gnorm
        else:
            # overflow: drop grads; the shared scaler applies hysteresis
            self._host_skipped += 1
        self._pipe_scaler.update_scale(not all_finite)
        if not all_finite:
            log_dist(f"PipelineEngine: OVERFLOW, skipping step "
                     f"{self.global_steps + 1}, scale -> "
                     f"{self._pipe_scaler.cur_scale:g}", ranks=[0])
            import jax.numpy as jnp

            for s in range(self.num_stages):
                with jax.set_mesh(self._submeshes[s]):
                    st = self.stage_states[s]
                    # zeros_like, NOT a*0.0: accum holds Inf/NaN here and
                    # inf*0 = NaN would poison every subsequent step
                    zero = jax.tree_util.tree_map(jnp.zeros_like, st.accum)
                    self.stage_states[s] = st._replace(accum=zero)

        self.global_steps += 1
        self.micro_steps += self.micro_batches
        self.tput_timer.stop()
        # one reduction + one transfer instead of gas scalar fetches
        with jax.set_mesh(self._submeshes[-1]):
            loss = float(jax.device_get(
                self._stage_jits[-1]["mean_scalar"](losses)))
        # mid-stage aux losses (MoE load balance) join the reported
        # objective so train_batch returns the same number regardless of
        # stage count (the last stage's own aux is already inside `loss`)
        for s, auxes in enumerate(mid_auxes):
            if auxes:
                with jax.set_mesh(self._submeshes[s]):
                    loss += float(jax.device_get(
                        self._stage_jits[s]["mean_scalar"](auxes)))
        self._last_loss = loss
        self._last_metrics = {
            "overflow": not all_finite,
            "grad_norm": getattr(self, "_last_grad_norm", 0.0),
            "loss_scale": scale, "loss": loss}
        self._observe_step_outcome(loss=loss, overflow=not all_finite)
        if self.global_steps % self.steps_per_print() == 0:
            self._report_progress(self.global_steps)
        return loss

    def eval_batch(self, data_iter=None, batch=None):
        """Forward-only pipelined evaluation (reference pipe/engine.py:320)."""
        import jax

        micros = self._collect_micros(data_iter, batch)
        self._ensure_pipe_state(micros[0])
        S = self.num_stages
        losses = []
        act = {}
        rng = jax.random.fold_in(self._pipe_rng, self.global_steps)
        # forward wavefront, double-buffered per the InferenceSchedule
        for mb, micro in enumerate(micros):
            x = self._put_stage(self.module.input_fn(micro), 0)
            for s in range(S):
                jits = self._stage_jits[s]
                with jax.set_mesh(self._submeshes[s]):
                    if s == S - 1:
                        batch_dev = self._put_stage(micro, s)
                        losses.append(jits["eval_loss"](
                            self.stage_states[s].params, x, rng, batch_dev))
                    else:
                        x = jits["eval_fwd"](self.stage_states[s].params, x, rng)
                        x = self._transfer(x, s + 1)
        out = float(np.mean([float(jax.device_get(l)) for l in losses]))
        if self._watchdog is not None:
            # eval between optimizer steps is progress, not a stalled step
            self._watchdog.heartbeat()
        return out

    def _collect_micros(self, data_iter, batch):
        gas = self.micro_batches
        if batch is not None:
            if isinstance(batch, dict):
                return [{k: v[i] for k, v in batch.items()} for i in range(gas)]
            return list(batch)
        assert data_iter is not None, "train_batch needs data_iter or batch"
        return [next(data_iter) for _ in range(gas)]

    def _exec_train_schedule(self, micros):
        """Execute TrainSchedule instruction streams for all stages,
        tick-aligned (the single-controller analog of reference
        _exec_schedule, pipe/engine.py:1148-1161)."""
        import jax

        S = self.num_stages
        scheds = [sched_lib.TrainSchedule(self.micro_batches, S, s)
                  for s in range(S)]
        streams = [list(sc.steps()) for sc in scheds]
        nbuf = [sc.num_pipe_buffers() for sc in scheds]

        # per-stage buffer slots
        in_act = [[None] * nbuf[s] for s in range(S)]    # fwd input (saved)
        out_act = [[None] * nbuf[s] for s in range(S)]   # fwd output
        in_grad = [[None] * nbuf[s] for s in range(S)]   # recv'd dL/dout
        out_grad = [[None] * nbuf[s] for s in range(S)]  # computed dL/din
        micro_dev = [[None] * nbuf[s] for s in range(S)] # loaded micro (0/last)
        load_ptr = [0] * S                               # next micro to load
        act_q = [deque() for _ in range(S)]   # edge s-1 -> s
        grad_q = [deque() for _ in range(S)]  # edge s+1 -> s
        losses = []
        mid_auxes = [[] for _ in range(S)]    # per-micro aux, mid stages
        base_rng = jax.random.fold_in(self._pipe_rng, self.global_steps)
        micro_rngs = [jax.random.fold_in(base_rng, i)
                      for i in range(self.micro_batches)]
        # every stage sees micro-batches in order, forward and backward both;
        # counters recover the micro id (and hence the SAME rng at fwd and at
        # the bwd recompute) without threading ids through buffers
        fwd_ptr = [0] * S
        bwd_ptr = [0] * S

        n_ticks = len(streams[0])
        for tick in range(n_ticks):
            # sends first so same-tick recvs are satisfied (the reference's
            # paired blocking broadcasts serialize the same way)
            for s in range(S):
                for cmd in streams[s][tick]:
                    if isinstance(cmd, sched_lib.SendActivation):
                        act_q[s + 1].append(
                            self._transfer(out_act[s][cmd.buffer_id], s + 1))
                    elif isinstance(cmd, sched_lib.SendGrad):
                        grad_q[s - 1].append(
                            self._transfer(out_grad[s][cmd.buffer_id], s - 1))
            for s in range(S):
                jits = self._stage_jits[s]
                st = self.stage_states[s]
                for cmd in streams[s][tick]:
                    buf = getattr(cmd, "buffer_id", None)
                    if isinstance(cmd, sched_lib.SendActivation) or \
                            isinstance(cmd, sched_lib.SendGrad):
                        continue
                    if isinstance(cmd, sched_lib.LoadMicroBatch):
                        micro = micros[load_ptr[s]]
                        load_ptr[s] += 1
                        if s == 0:
                            in_act[s][buf] = self._put_stage(
                                self.module.input_fn(micro), 0)
                        if s == S - 1:
                            micro_dev[s][buf] = self._put_stage(micro, s)
                    elif isinstance(cmd, sched_lib.RecvActivation):
                        in_act[s][buf] = act_q[s].popleft()
                    elif isinstance(cmd, sched_lib.RecvGrad):
                        in_grad[s][buf] = grad_q[s].popleft()
                    elif isinstance(cmd, sched_lib.ForwardPass):
                        rng = micro_rngs[fwd_ptr[s]]
                        fwd_ptr[s] += 1
                        with jax.set_mesh(self._submeshes[s]):
                            if s < S - 1:
                                out_act[s][buf] = jits["fwd"](
                                    st.params, in_act[s][buf], rng)
                            # last stage: loss computed in backward (fused)
                    elif isinstance(cmd, sched_lib.BackwardPass):
                        rng = micro_rngs[bwd_ptr[s]]
                        bwd_ptr[s] += 1
                        with jax.set_mesh(self._submeshes[s]):
                            if s == S - 1:
                                new_accum, gx, loss = jits["bwd_last"](
                                    st.params, st.accum, in_act[s][buf], rng,
                                    micro_dev[s][buf],
                                    np.float32(self._pipe_scaler.cur_scale))
                                losses.append(loss)
                            else:
                                new_accum, gx, aux = jits["bwd_mid"](
                                    st.params, st.accum, in_act[s][buf], rng,
                                    in_grad[s][buf],
                                    np.float32(self._pipe_scaler.cur_scale))
                                if self._module_has_aux:
                                    mid_auxes[s].append(aux)
                            self.stage_states[s] = st._replace(
                                accum=new_accum)
                            st = self.stage_states[s]
                            out_grad[s][buf] = gx
                        # free consumed buffers
                        in_grad[s][buf] = None
                    elif isinstance(cmd, sched_lib.ReduceTiedGrads):
                        # every stage's stream emits this at the last tick;
                        # the reduction is global, run it exactly once
                        if s == 0:
                            self._reduce_tied_grads()
                        st = self.stage_states[s]
                    elif isinstance(cmd, (sched_lib.ReduceGrads,
                                          sched_lib.OptimizerStep)):
                        # ReduceGrads: psum already inside backward jits;
                        # OptimizerStep: host-coordinated in train_batch
                        pass
                    else:  # pragma: no cover
                        raise AssertionError(f"unknown instruction {cmd}")
        return losses, mid_auxes

    def _reduce_tied_grads(self):
        """Sum tied-param grad accumulators across tie-group stages and
        redistribute so each member applies the identical update. Stays on
        device: peers' accum shards transfer over ICI (device_put to the
        target submesh) and sum inside a jitted add — no host round-trip."""
        import jax

        groups = self.module.tied_groups(self.num_stages)
        for key, stages in groups.items():
            pkey = f"tied_{key}"
            # snapshot pre-reduction accums: summing in place would make
            # later targets double-count already-reduced members
            originals = {s: self.stage_states[s].accum[pkey] for s in stages}
            for target in stages:
                total = originals[target]
                with jax.set_mesh(self._submeshes[target]):
                    for s in stages:
                        if s == target:
                            continue
                        peer = jax.tree_util.tree_map(
                            lambda l, ref: jax.device_put(l, ref.sharding),
                            originals[s], total)
                        total = jax.tree_util.tree_map(
                            lambda a, b: a + b, total, peer)
                accum = dict(self.stage_states[target].accum)
                accum[pkey] = total
                self.stage_states[target] = \
                    self.stage_states[target]._replace(accum=accum)

    # ------------------------------------------------------------------
    # checkpointing (pipeline layout: per-stage state files)
    # ------------------------------------------------------------------
    def _layer_key_set(self):
        """Stage-count-independent universe of layer param keys: layer-
        granular files are keyed by these, so a checkpoint written at pp=N
        can be read at pp=M (reference pipe/module.py:536-567 writes
        layer_XX-model_states files for the same reason)."""
        return {layer.param_key for layer in self.module._layers
                if layer.param_key is not None}

    @staticmethod
    def _path_layer_key(path, layer_keys):
        import jax

        for p in path:
            if isinstance(p, jax.tree_util.DictKey) and str(p.key) in layer_keys:
                return str(p.key)
        return None

    def _stage_save_tree(self, st):
        """The persisted slice of a StageState. accum is excluded: steps only
        complete at accumulation boundaries, where it is zeros."""
        return {"params": st.params, "master": st.master,
                "opt_state": st.opt_state}

    def _chaos_poison_accum(self):
        """Pipeline variant of the chaos NaN-grad hook: the accumulator
        lives per stage, not on a single TrainState."""
        from deepspeed_tpu.runtime.resilience import chaos

        if chaos.active() is None or not chaos.consume_nan_grad_step():
            return
        import jax
        import jax.numpy as jnp

        for s in range(self.num_stages):
            with jax.set_mesh(self._submeshes[s]):
                st = self.stage_states[s]
                poisoned = jax.tree_util.tree_map(
                    lambda a: jnp.full_like(a, jnp.nan), st.accum)
                self.stage_states[s] = st._replace(accum=poisoned)

    def _assert_saveable(self):
        assert self.stage_states is not None, "no pipeline state to save"

    def _assert_loadable(self):
        assert self.stage_states is not None, \
            "run one batch (or _ensure_pipe_state) before load_checkpoint"

    def _write_checkpoint_files(self, path, client_state, backend):
        """Pipeline payload: layer-granular layout — one file per layer
        param key, entries keyed by the leaf's tree path (identical no
        matter which stage owns the layer), plus a 'globals' file for
        layer-independent optimizer scalars (identical on every stage).
        Runs inside the parent's atomic commit path: ``path`` is the tag
        temp dir and each write feeds the chaos fault-injection hooks."""
        if backend not in (None, "auto", "npz", "npz-layer"):
            raise ValueError(
                f"pipeline checkpoints only support the layer-granular npz "
                f"backend; got backend={backend!r}")
        import jax

        from deepspeed_tpu.runtime.checkpoint_utils import named_leaf_entry
        from deepspeed_tpu.runtime.resilience import chaos

        layer_keys = self._layer_key_set()
        per_layer = {}
        global_leaves = {}
        for st in self.stage_states:
            host = jax.device_get(self._stage_save_tree(st))
            for p, leaf in jax.tree_util.tree_flatten_with_path(host)[0]:
                entry = named_leaf_entry(jax.tree_util.keystr(p), leaf)
                k = self._path_layer_key(p, layer_keys)
                if k is None:
                    global_leaves.update(entry)
                else:
                    per_layer.setdefault(k, {}).update(entry)
        for k, entries in per_layer.items():
            fname = os.path.join(path, f"{k}-states.npz")
            self._ckpt_savez(fname, **entries)
            chaos.file_written(fname)
        fname = os.path.join(path, "globals-states.npz")
        self._ckpt_savez(fname, **global_leaves)
        chaos.file_written(fname)
        meta = {
            "global_steps": self.global_steps,
            "micro_steps": self.micro_steps,
            "skipped_steps": self._host_skipped,
            "cur_scale": self._pipe_scaler.cur_scale,
            "scaler_state": self._pipe_scaler.__dict__.copy(),
            "num_stages": self.num_stages,
            "partition": self.module.partition_layers(self.num_stages),
            "layer_keys": sorted(layer_keys),
            "format": "layer-granular",
            "lr_scheduler": self.lr_scheduler.state_dict()
            if self.lr_scheduler is not None else None,
            "client_state": client_state,
        }
        fname = os.path.join(path, "metadata.pkl")
        with open(fname, "wb") as f:
            pickle.dump(meta, f)
        chaos.file_written(fname)
        log_dist(f"Wrote pipeline checkpoint payload "
                 f"({len(per_layer)} layer files)", ranks=[0])
        return "npz-layer"

    def _ckpt_state_snapshot(self):
        snap = super()._ckpt_state_snapshot()
        snap["stage_states"] = list(self.stage_states) \
            if self.stage_states is not None else None
        snap["pipe_scaler"] = dict(self._pipe_scaler.__dict__) \
            if getattr(self, "_pipe_scaler", None) is not None else None
        return snap

    def _ckpt_state_restore(self, snap):
        super()._ckpt_state_restore(snap)
        if snap.get("stage_states") is not None:
            self.stage_states = snap["stage_states"]
        if snap.get("pipe_scaler") is not None:
            self._pipe_scaler.__dict__.update(snap["pipe_scaler"])

    def _load_checkpoint_tag(self, load_dir, tag, load_module_strict=True,
                             load_optimizer_states=True,
                             load_lr_scheduler_states=True):
        import jax

        path = os.path.join(load_dir, str(tag))
        with open(os.path.join(path, "metadata.pkl"), "rb") as f:
            meta = pickle.load(f)
        assert meta.get("format") == "layer-granular", \
            "pre-round-4 per-stage pipeline checkpoints are not readable; " \
            "re-save with this version"
        assert self.stage_states is not None, \
            "run one batch (or _ensure_pipe_state) before load_checkpoint"
        layer_keys = self._layer_key_set()
        saved_keys = set(meta.get("layer_keys", []))
        if load_module_strict:
            assert saved_keys == layer_keys, \
                (f"checkpoint layers {sorted(saved_keys)} != module layers "
                 f"{sorted(layer_keys)}")

        from deepspeed_tpu.runtime.checkpoint_utils import named_leaf_lookup

        files = {}

        def lookup(k, name):
            fname = "globals-states.npz" if k is None else f"{k}-states.npz"
            if fname not in files:
                files[fname] = np.load(os.path.join(path, fname))
            return named_leaf_lookup(files[fname], name)

        # rebuild each (possibly re-partitioned) stage from the layer files:
        # every leaf of the fresh stage state is looked up by (layer key,
        # tree path), which is stage-layout independent
        new_states = []
        for st in self.stage_states:
            tpl = jax.device_get(self._stage_save_tree(st))
            leaves, treedef = jax.tree_util.tree_flatten_with_path(tpl)
            restored = [lookup(self._path_layer_key(p, layer_keys),
                               jax.tree_util.keystr(p))
                        for p, _ in leaves]
            host = jax.tree_util.tree_unflatten(treedef, restored)
            ref = self._stage_save_tree(st)
            dev = jax.tree_util.tree_map(
                lambda l, r: jax.device_put(l, r.sharding), host, ref)
            new_states.append(st._replace(
                params=dev["params"], master=dev["master"],
                opt_state=dev["opt_state"]))
        self.stage_states = new_states
        self.global_steps = meta["global_steps"]
        self.micro_steps = meta["micro_steps"]
        self._host_skipped = meta["skipped_steps"]
        self._pipe_scaler.cur_scale = meta["cur_scale"]
        for k, v in meta.get("scaler_state", {}).items():
            setattr(self._pipe_scaler, k, v)
        if load_lr_scheduler_states and self.lr_scheduler is not None \
                and meta.get("lr_scheduler") is not None:
            self.lr_scheduler.load_state_dict(meta["lr_scheduler"])
        log_dist(f"Loaded pipeline checkpoint {path}", ranks=[0])
        return path, meta.get("client_state", {})
