"""Pipeline instruction schedules — declarative streams driving the engine.

Reference behavior: deepspeed/runtime/pipe/schedule.py:6-482. The schedule is
an algorithm spec, not an implementation detail: TrainSchedule emits the
1F1B-interleaved stream (even/odd step -> micro-batch mapping, buffer count =
min(stages - stage + 1, micro_batches)); the TPU engine executes it
host-driven: each instruction is a jitted per-stage call, sends are
device_put between adjacent stage submeshes (runtime/pipe/engine.py).

Why host-driven (and not one fused whole-schedule lax.scan): dispatch is
asynchronous — the host enqueues every stage's program for a tick without
waiting, so stage programs overlap on-device exactly as 1F1B intends, and
the host cost is enqueue-only (measured by tools/pipe_bench.py; numbers in
BENCH_NOTES.md). A single fused scan would need every stage's weights and
buffers resident in ONE program over the whole mesh with uniform tick
bodies, giving up heterogeneous stage partitions and per-stage remat
choices; the measured enqueue overhead does not justify that trade.
"""


class PipeInstruction:
    """Namedtuple-style instruction; kwargs become attributes.
    Reference: schedule.py:336-356."""

    def __init__(self, **kwargs):
        self.name = self.__class__.__name__
        self.kwargs = kwargs
        for k, v in kwargs.items():
            setattr(self, k, v)

    def __repr__(self):
        args = ", ".join(f"{k}={v!r}" for k, v in self.kwargs.items())
        return f"{self.name}({args})"

    def __eq__(self, other):
        return (type(self) is type(other)) and self.kwargs == other.kwargs

    def __hash__(self):
        return hash((self.name, tuple(sorted(self.kwargs.items()))))


class OptimizerStep(PipeInstruction):
    """Step the optimizer and zero gradients; after Reduce*Grads."""


# Compiled-schedule buffer-op instructions additionally carry:
#   chunk_id  — the stage-LOCAL model-chunk index (interleaved virtual
#               stages; 0 when v=1). Global chunk = chunk_id*stages + stage.
#   micro_id  — the micro-batch this op processes (explicit, so the engine
#               never has to recover it from visit-order counters).


class ReduceGrads(PipeInstruction):
    """Data-parallel gradient reduction within the stage."""


class ReduceTiedGrads(PipeInstruction):
    """All-reduce gradients of tied modules over their tie group."""


class BufferOpInstruction(PipeInstruction):
    def __init__(self, buffer_id, **kwargs):
        super().__init__(buffer_id=buffer_id, **kwargs)


class LoadMicroBatch(BufferOpInstruction):
    """Load a micro-batch into buffer_id (first/last stages only)."""


class ForwardPass(BufferOpInstruction):
    """Run forward on buffer_id's activations."""


class BackwardPass(BufferOpInstruction):
    """Run backward with buffer_id's received output grads."""


class SendActivation(BufferOpInstruction):
    """Send buffer_id's activations to the next stage."""


class RecvActivation(BufferOpInstruction):
    """Receive activations from the previous stage into buffer_id."""


class SendGrad(BufferOpInstruction):
    """Send buffer_id's input grads to the previous stage."""


class RecvGrad(BufferOpInstruction):
    """Receive output grads from the next stage into buffer_id."""


class BackwardGradPass(BufferOpInstruction):
    """Zero-bubble dgrad: input grads only (vjp w.r.t. x); the weight
    gradient is deferred to a later BackwardWeightPass. The buffer's
    saved input activation and received output grad stay LIVE."""


class BackwardWeightPass(BufferOpInstruction):
    """Zero-bubble wgrad: the deferred vjp w.r.t. params into the grad
    accumulator; frees the buffer's activation and output grad."""


def _even(x):
    return x % 2 == 0


class PipeSchedule:
    """Generator of per-step instruction lists for one stage; each yielded
    step is barrier-safe. Reference: schedule.py:6-127."""

    def __init__(self, micro_batches, stages, stage_id):
        self.micro_batches = micro_batches
        self.stages = stages
        self.stage_id = stage_id
        self.prev_stage = stage_id - 1
        self.next_stage = stage_id + 1

    def steps(self):
        raise NotImplementedError

    def num_pipe_buffers(self):
        return self.micro_batches

    def _valid_micro_batch(self, mb):
        return 0 <= mb < self.micro_batches

    def _valid_stage(self, stage):
        return 0 <= stage < self.stages

    @property
    def stage(self):
        return self.stage_id

    @property
    def num_stages(self):
        return self.stages

    @property
    def num_micro_batches(self):
        return self.micro_batches

    @property
    def is_first_stage(self):
        return self.stage_id == 0

    @property
    def is_last_stage(self):
        return self.stage_id == self.stages - 1

    def _buffer_idx(self, mb):
        assert self._valid_micro_batch(mb)
        return mb % self.num_pipe_buffers()

    def __iter__(self):
        return iter(self.steps())


class InferenceSchedule(PipeSchedule):
    """Forward-only wavefront; double-buffered. Reference: schedule.py:129-181."""

    def steps(self):
        for step_id in range(self.micro_batches + self.stages - 1):
            mb = step_id - self.stage_id
            cmds = []
            if _even(self.stage_id):
                recv_buf, send_buf = step_id % 2, (step_id + 1) % 2
            else:
                recv_buf, send_buf = (step_id + 1) % 2, step_id % 2

            if (self.is_first_stage or self.is_last_stage) \
                    and self._valid_micro_batch(mb):
                cmds.append(LoadMicroBatch(recv_buf))

            # even stages send-then-recv, odd stages recv-then-send, so
            # paired blocking exchanges can't deadlock
            def _send():
                if self._valid_stage(self.next_stage) \
                        and self._valid_micro_batch(mb - 1):
                    cmds.append(SendActivation(send_buf))

            def _recv():
                if self._valid_stage(self.prev_stage) \
                        and self._valid_micro_batch(mb):
                    cmds.append(RecvActivation(recv_buf))

            if _even(self.stage_id):
                _send(), _recv()
            else:
                _recv(), _send()

            if self._valid_micro_batch(mb):
                cmds.append(ForwardPass(recv_buf))
            yield cmds

    def num_pipe_buffers(self):
        return 2


class TrainSchedule(PipeSchedule):
    """1F1B-interleaved training stream. Reference: schedule.py:183-289.

    Total 2*(micro_batches + stages - 1) ticks; each tick maps to a
    (micro_batch, is_forward) pair via the even/odd parity of tick and stage,
    interleaving one forward with one backward in steady state.
    """

    def steps(self):
        prev_mb = -1
        total = 2 * (self.micro_batches + self.stages - 1)
        for step_id in range(total):
            mb, is_forward = self._step_to_micro_batch(step_id)
            cmds = []

            # activation/grad exchange with the neighbor stages
            if is_forward:
                if self._valid_stage(self.prev_stage):
                    if self._valid_micro_batch(mb):
                        cmds.append(RecvActivation(self._buffer_idx(mb)))
                    if self._valid_micro_batch(prev_mb):
                        cmds.append(SendGrad(self._buffer_idx(prev_mb)))
            else:
                if self._valid_stage(self.next_stage):
                    if self._valid_micro_batch(prev_mb):
                        cmds.append(SendActivation(self._buffer_idx(prev_mb)))
                    if self._valid_micro_batch(mb):
                        cmds.append(RecvGrad(self._buffer_idx(mb)))

            if (self.is_first_stage or self.is_last_stage) \
                    and is_forward and self._valid_micro_batch(mb):
                cmds.append(LoadMicroBatch(self._buffer_idx(mb)))

            if self._valid_micro_batch(mb):
                cmds.append(ForwardPass(self._buffer_idx(mb)) if is_forward
                            else BackwardPass(self._buffer_idx(mb)))

            if step_id == total - 1:
                cmds.append(ReduceTiedGrads())
                cmds.append(ReduceGrads())
                cmds.append(OptimizerStep())

            prev_mb = mb
            yield cmds

    def num_pipe_buffers(self):
        """Distance to the last stage bounds in-flight micro-batches
        (reference schedule.py:243)."""
        return max(2, min(self.stages - self.stage_id + 1, self.micro_batches))

    def _step_to_micro_batch(self, step_id):
        """Even ticks run forwards on even stages / backwards on odd stages,
        and vice versa — the phase shift that interleaves 1F1B."""
        base = step_id // 2
        if _even(step_id) == _even(self.stage_id):
            # forward tick for this stage
            if _even(step_id):
                mb = base - self.stage_id // 2
            else:
                mb = (step_id - 1) // 2 - self.stage_id // 2
            return mb, True
        # backward tick
        if _even(step_id):
            mb = base - self.stages + (self.stage_id + 1) // 2
        else:
            mb = (step_id - 1) // 2 - self.stages + 1 + self.stage_id // 2
        return mb, False


#######################################################################
# Compiled schedules — interleaved virtual stages and zero-bubble ZB-H1
#
# The generator classes above describe per-stage streams in closed form;
# the better schedules below are PLANNED instead: a per-stage ordered list
# of compute ops (F / B / Bd / W per micro per chunk) is derived (Megatron
# interleaving order, arXiv 2104.04473; ZB-H1 wgrad deferral, arXiv
# 2401.10241), then lowered to an instruction stream with explicit buffer
# slots, chunk ids and micro ids. The engine executes compiled streams
# with queue semantics (a Recv blocks until its Send ran); timing/bubble
# claims about them are made by runtime/pipe/bubble_accounting.py, which
# replays any compiled schedule tick-by-tick against a cost model.
#######################################################################

SCHEDULE_1F1B = "1f1b"
SCHEDULE_INTERLEAVED = "interleaved"
SCHEDULE_ZB_H1 = "zb-h1"
KNOWN_SCHEDULES = (SCHEDULE_1F1B, SCHEDULE_INTERLEAVED, SCHEDULE_ZB_H1)


class _SlotAllocator:
    """Lowest-free-index buffer slots for one chunk; high-water = the
    buffer count the engine must allocate."""

    def __init__(self):
        self._free = []
        self._next = 0
        self.high_water = 0

    def alloc(self):
        if self._free:
            return self._free.pop(0)
        slot = self._next
        self._next += 1
        self.high_water = max(self.high_water, self._next)
        return slot

    def release(self, slot):
        assert slot not in self._free, f"double free of buffer slot {slot}"
        self._free.append(slot)
        self._free.sort()


class CompiledSchedule:
    """A planned training schedule, lowered to per-physical-stage flat
    instruction lists with explicit chunk/micro ids and buffer slots.

    streams[s] is executed in order by stage s; cross-stage data moves
    through per-(global chunk, kind) FIFO queues, so the only ordering
    contract is send-before-matching-recv (the engine blocks, the
    bubble simulator proves deadlock freedom).

    ``stash=True`` marks a zero-bubble schedule compiled for activation
    STASHING: each ForwardPass additionally fills a stash slot (the vjp
    residuals of the single forward) that stays live until the micro's
    BackwardWeightPass frees it — dgrad and wgrad consume the stash
    instead of recomputing the forward.  Stash slots reuse the stream's
    explicit buffer_ids (the F->W lifetime IS the buffer lifetime in a
    zb stream), so ``num_stash_slots`` per chunk equals ``num_buffers``
    there and is 0 for schedules compiled without stashing — executors
    and tools must refuse to run stash-mode cost models against a
    schedule whose slots were never emitted."""

    def __init__(self, name, micro_batches, stages, virtual_stages,
                 streams, num_buffers, stash=False):
        self.name = name
        self.micro_batches = micro_batches
        self.stages = stages
        self.virtual_stages = virtual_stages
        self.num_chunks = stages * virtual_stages
        self.streams = streams            # list[stages] of instruction lists
        self.num_buffers = num_buffers    # list[num_chunks] buffer slots
        self.stash = stash
        self.num_stash_slots = list(num_buffers) if stash \
            else [0] * len(num_buffers)

    def global_chunk(self, stage_id, chunk_id):
        return chunk_id * self.stages + stage_id

    def __repr__(self):
        return (f"CompiledSchedule({self.name}, micro={self.micro_batches}, "
                f"stages={self.stages}, v={self.virtual_stages}"
                f"{', stash' if self.stash else ''})")


def _order_1f1b(micro_batches, stages, stage_id, bwd_op="B"):
    """Classic 1F1B compute-op order for one stage: warmup forwards, then
    strict 1-forward-1-backward alternation, then cooldown backwards."""
    warmup = min(micro_batches, stages - stage_id - 1)
    ops = [("F", m, 0) for m in range(warmup)]
    fnext, bnext = warmup, 0
    while bnext < micro_batches:
        if fnext < micro_batches:
            ops.append(("F", fnext, 0))
            fnext += 1
        ops.append((bwd_op, bnext, 0))
        bnext += 1
    return ops


def _order_interleaved(micro_batches, stages, virtual_stages, stage_id):
    """Megatron interleaved-1F1B compute-op order for one stage (reference:
    megatron/core/pipeline_parallel/schedules.py, forward_backward_
    pipelining_with_interleaving). Requires micro_batches % stages == 0."""
    S, v, M = stages, virtual_stages, micro_batches
    assert M % S == 0, "interleaved schedule needs micro_batches % stages == 0"
    total = M * v

    def fchunk(k):
        return (k % (S * v)) // S

    def micro(k):
        return (k // (S * v)) * S + (k % S)

    if M == S:
        warmup = total
    else:
        warmup = min(total, (S - stage_id - 1) * 2 + (v - 1) * S)
    ops = [("F", micro(k), fchunk(k)) for k in range(warmup)]
    for i in range(total - warmup):
        k_f, k_b = warmup + i, i
        ops.append(("F", micro(k_f), fchunk(k_f)))
        ops.append(("B", micro(k_b), v - 1 - fchunk(k_b)))
    for k in range(total - warmup, total):
        ops.append(("B", micro(k), v - 1 - fchunk(k)))
    return ops


def _plan_zb_h1(micro_batches, stages, fwd_cost=1.0, dgrad_cost=1.5,
                wgrad_cost=1.5, max_live=None):
    """ZB-H1 (arXiv 2401.10241 fig. 4) op orders for all stages: the 1F1B
    mainline with backwards split into dgrad (Bd, stays on the critical
    path) and wgrad (W, deferred into bubble slots by a greedy timing
    simulation). ``max_live`` caps in-flight micro-batches per stage (a
    forced W runs before a forward that would exceed it). The default cap
    min(S, M) on EVERY stage keeps the worst-stage activation peak (stage
    0, which sizes uniformly-provisioned devices) identical to 1F1B while
    reaching the paper's H1 bubble; later stages hold up to that many
    in-flight micros instead of 1F1B's S-s."""
    S, M = stages, micro_batches
    mains = [_order_1f1b(M, S, s, bwd_op="Bd") for s in range(S)]
    if max_live is None:
        max_live = [max(2, min(S, M))] * S
    idx = [0] * S
    free_t = [0.0] * S
    pending_w = [[] for _ in range(S)]    # micros with Bd done, W not yet
    live = [0] * S                        # micros with F done, W not yet
    orders = [[] for _ in range(S)]
    f_done, d_done = {}, {}               # (micro, stage) -> finish time

    def dep_time(op, m, s):
        """Cross-stage readiness time, or None if the producer has not been
        simulated yet (decide later)."""
        if op == "F":
            return 0.0 if s == 0 else f_done.get((m, s - 1))
        return 0.0 if s == S - 1 else d_done.get((m, s + 1))

    def run_w(s):
        m = pending_w[s].pop(0)
        orders[s].append(("W", m, 0))
        free_t[s] += wgrad_cost
        live[s] -= 1

    done = lambda: all(i >= len(mains[s]) and not pending_w[s]  # noqa: E731
                       for s, i in enumerate(idx))
    while not done():
        progressed = False
        for s in range(S):
            if idx[s] >= len(mains[s]):
                while pending_w[s]:                 # cooldown: drain wgrads
                    run_w(s)
                    progressed = True
                continue
            op, m, _ = mains[s][idx[s]]
            if op == "F" and live[s] >= max_live[s] and pending_w[s]:
                run_w(s)                            # memory cap: W first
                progressed = True
                continue
            t_dep = dep_time(op, m, s)
            if t_dep is None:
                continue                            # producer not planned yet
            if t_dep > free_t[s] and pending_w[s]:
                run_w(s)                            # bubble slot: fill with W
                progressed = True
                continue
            start = max(free_t[s], t_dep)
            if op == "F":
                free_t[s] = start + fwd_cost
                f_done[(m, s)] = free_t[s]
                live[s] += 1
            else:
                free_t[s] = start + dgrad_cost
                d_done[(m, s)] = free_t[s]
                pending_w[s].append(m)
            orders[s].append((op, m, 0))
            idx[s] += 1
            progressed = True
        assert progressed, "zb-h1 planner wedged (mainline not 1F1B-feasible)"
    return orders


def _emit_streams(orders, stages):
    """Lower per-stage compute-op orders [(op, micro, local_chunk), ...]
    into instruction streams with explicit buffer slots. Returns
    (streams, num_buffers) with num_buffers per GLOBAL chunk."""
    S = stages
    num_chunks = 1 + max(c * S + s for s, ops in enumerate(orders)
                         for _, _, c in ops) if any(orders) else S
    slots = [_SlotAllocator() for _ in range(num_chunks)]
    buf_of = {}                            # (micro, global chunk) -> slot
    streams = [[] for _ in range(S)]

    # Buffer lifetimes interleave across stages in wall-clock order, not
    # per-stage stream order; allocate by replaying all stages' ops in a
    # dependency-consistent global order. Round-robin one op per stage per
    # pass preserves each stage's order and is feasible whenever the
    # schedule itself is (the engine executes with the same discipline).
    idx = [0] * S
    fwd_seen = [set() for _ in range(num_chunks)]
    bwd_seen = [set() for _ in range(num_chunks)]

    def emit(s, op, m, c):
        g = c * S + s
        out = streams[s]
        if op == "F":
            buf = slots[g].alloc()
            buf_of[(m, g)] = buf
            kw = dict(chunk_id=c, micro_id=m)
            if g == 0:
                out.append(LoadMicroBatch(buf, **kw))
            else:
                out.append(RecvActivation(buf, **kw))
            if g == num_chunks - 1 and g != 0:
                out.append(LoadMicroBatch(buf, **kw))   # labels for the loss
            out.append(ForwardPass(buf, **kw))
            if g < num_chunks - 1:
                out.append(SendActivation(buf, **kw))
            fwd_seen[g].add(m)
        else:
            buf = buf_of[(m, g)]
            kw = dict(chunk_id=c, micro_id=m)
            if op in ("B", "Bd"):
                if g < num_chunks - 1:
                    out.append(RecvGrad(buf, **kw))
                out.append(BackwardPass(buf, **kw) if op == "B"
                           else BackwardGradPass(buf, **kw))
                if g > 0:
                    out.append(SendGrad(buf, **kw))
                bwd_seen[g].add(m)
            if op in ("B", "W"):
                if op == "W":
                    out.append(BackwardWeightPass(buf, **kw))
                slots[g].release(buf)
                del buf_of[(m, g)]

    def ready(s):
        op, m, c = orders[s][idx[s]]
        g = c * S + s
        if op == "F":
            return g == 0 or m in fwd_seen[g - 1]
        if op in ("B", "Bd"):
            return g == num_chunks - 1 or m in bwd_seen[g + 1]
        return True                                     # W: stage-local

    while any(i < len(orders[s]) for s, i in enumerate(idx)):
        progressed = False
        for s in range(S):
            if idx[s] >= len(orders[s]) or not ready(s):
                continue
            emit(s, *orders[s][idx[s]])
            idx[s] += 1
            progressed = True
        assert progressed, "schedule op order is not dependency-feasible"
    return streams, [a.high_water for a in slots]


def compile_schedule(name, micro_batches, stages, virtual_stages=1,
                     stash=False):
    """Build the CompiledSchedule for a training batch.

    1f1b        — the classic schedule (identical math/op order to
                  TrainSchedule, lowered to the compiled form);
    interleaved — Megatron virtual stages: each physical stage owns
                  ``virtual_stages`` non-contiguous model chunks, shrinking
                  the pipeline bubble by ~1/v at the cost of (v-1) extra
                  p2p boundary crossings per micro;
    zb-h1       — zero-bubble H1: backwards split into dgrad/wgrad, wgrads
                  deferred into bubble slots.  ``stash=True`` compiles the
                  activation-STASHING variant: the greedy wgrad placement
                  is timed at dgrad = wgrad = 1 (neither split pass pays a
                  forward recompute — both consume the forward's stashed
                  vjp residuals) and every buffer slot doubles as a stash
                  slot (CompiledSchedule.num_stash_slots).

    Callers gate/fall back (with DISARMED warnings) BEFORE calling; this
    function asserts hard on violated preconditions.
    """
    M, S, v = micro_batches, stages, virtual_stages
    assert not stash or name == SCHEDULE_ZB_H1, \
        "activation stashing composes with the zb-h1 schedule only (the " \
        "fused backward of 1f1b/interleaved already recomputes exactly once)"
    if name == SCHEDULE_1F1B:
        assert v == 1, "1f1b has no virtual stages"
        orders = [_order_1f1b(M, S, s) for s in range(S)]
    elif name == SCHEDULE_INTERLEAVED:
        assert v >= 2 and S >= 2
        orders = [_order_interleaved(M, S, v, s) for s in range(S)]
    elif name == SCHEDULE_ZB_H1:
        assert v == 1, "zb-h1 composes with v=1 only"
        assert S >= 2
        if stash:
            orders = _plan_zb_h1(M, S, fwd_cost=1.0, dgrad_cost=1.0,
                                 wgrad_cost=1.0)
        else:
            orders = _plan_zb_h1(M, S)
    else:
        raise KeyError(f"unknown pipeline schedule {name!r}; "
                       f"known: {KNOWN_SCHEDULES}")
    streams, num_buffers = _emit_streams(orders, S)
    while len(num_buffers) < S * v:       # chunks that never got a slot
        num_buffers.append(1)
    return CompiledSchedule(name, M, S, v, streams, num_buffers, stash=stash)


class DataParallelSchedule(PipeSchedule):
    """Plain gradient-accumulation DP expressed as a pipe schedule.
    Reference: schedule.py:292-318."""

    def steps(self):
        for step_id in range(self.micro_batches):
            cmds = [LoadMicroBatch(0), ForwardPass(0), BackwardPass(0)]
            if step_id == self.micro_batches - 1:
                cmds.extend([ReduceGrads(), OptimizerStep()])
            yield cmds

    def num_pipe_buffers(self):
        return 1
