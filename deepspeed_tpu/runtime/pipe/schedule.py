"""Pipeline instruction schedules — declarative streams driving the engine.

Reference behavior: deepspeed/runtime/pipe/schedule.py:6-482. The schedule is
an algorithm spec, not an implementation detail: TrainSchedule emits the
1F1B-interleaved stream (even/odd step -> micro-batch mapping, buffer count =
min(stages - stage + 1, micro_batches)); the TPU engine executes it
host-driven: each instruction is a jitted per-stage call, sends are
device_put between adjacent stage submeshes (runtime/pipe/engine.py).

Why host-driven (and not one fused whole-schedule lax.scan): dispatch is
asynchronous — the host enqueues every stage's program for a tick without
waiting, so stage programs overlap on-device exactly as 1F1B intends, and
the host cost is enqueue-only (measured by tools/pipe_bench.py; numbers in
BENCH_NOTES.md). A single fused scan would need every stage's weights and
buffers resident in ONE program over the whole mesh with uniform tick
bodies, giving up heterogeneous stage partitions and per-stage remat
choices; the measured enqueue overhead does not justify that trade.
"""


class PipeInstruction:
    """Namedtuple-style instruction; kwargs become attributes.
    Reference: schedule.py:336-356."""

    def __init__(self, **kwargs):
        self.name = self.__class__.__name__
        self.kwargs = kwargs
        for k, v in kwargs.items():
            setattr(self, k, v)

    def __repr__(self):
        args = ", ".join(f"{k}={v!r}" for k, v in self.kwargs.items())
        return f"{self.name}({args})"

    def __eq__(self, other):
        return (type(self) is type(other)) and self.kwargs == other.kwargs

    def __hash__(self):
        return hash((self.name, tuple(sorted(self.kwargs.items()))))


class OptimizerStep(PipeInstruction):
    """Step the optimizer and zero gradients; after Reduce*Grads."""


class ReduceGrads(PipeInstruction):
    """Data-parallel gradient reduction within the stage."""


class ReduceTiedGrads(PipeInstruction):
    """All-reduce gradients of tied modules over their tie group."""


class BufferOpInstruction(PipeInstruction):
    def __init__(self, buffer_id, **kwargs):
        super().__init__(buffer_id=buffer_id, **kwargs)


class LoadMicroBatch(BufferOpInstruction):
    """Load a micro-batch into buffer_id (first/last stages only)."""


class ForwardPass(BufferOpInstruction):
    """Run forward on buffer_id's activations."""


class BackwardPass(BufferOpInstruction):
    """Run backward with buffer_id's received output grads."""


class SendActivation(BufferOpInstruction):
    """Send buffer_id's activations to the next stage."""


class RecvActivation(BufferOpInstruction):
    """Receive activations from the previous stage into buffer_id."""


class SendGrad(BufferOpInstruction):
    """Send buffer_id's input grads to the previous stage."""


class RecvGrad(BufferOpInstruction):
    """Receive output grads from the next stage into buffer_id."""


def _even(x):
    return x % 2 == 0


class PipeSchedule:
    """Generator of per-step instruction lists for one stage; each yielded
    step is barrier-safe. Reference: schedule.py:6-127."""

    def __init__(self, micro_batches, stages, stage_id):
        self.micro_batches = micro_batches
        self.stages = stages
        self.stage_id = stage_id
        self.prev_stage = stage_id - 1
        self.next_stage = stage_id + 1

    def steps(self):
        raise NotImplementedError

    def num_pipe_buffers(self):
        return self.micro_batches

    def _valid_micro_batch(self, mb):
        return 0 <= mb < self.micro_batches

    def _valid_stage(self, stage):
        return 0 <= stage < self.stages

    @property
    def stage(self):
        return self.stage_id

    @property
    def num_stages(self):
        return self.stages

    @property
    def num_micro_batches(self):
        return self.micro_batches

    @property
    def is_first_stage(self):
        return self.stage_id == 0

    @property
    def is_last_stage(self):
        return self.stage_id == self.stages - 1

    def _buffer_idx(self, mb):
        assert self._valid_micro_batch(mb)
        return mb % self.num_pipe_buffers()

    def __iter__(self):
        return iter(self.steps())


class InferenceSchedule(PipeSchedule):
    """Forward-only wavefront; double-buffered. Reference: schedule.py:129-181."""

    def steps(self):
        for step_id in range(self.micro_batches + self.stages - 1):
            mb = step_id - self.stage_id
            cmds = []
            if _even(self.stage_id):
                recv_buf, send_buf = step_id % 2, (step_id + 1) % 2
            else:
                recv_buf, send_buf = (step_id + 1) % 2, step_id % 2

            if (self.is_first_stage or self.is_last_stage) \
                    and self._valid_micro_batch(mb):
                cmds.append(LoadMicroBatch(recv_buf))

            # even stages send-then-recv, odd stages recv-then-send, so
            # paired blocking exchanges can't deadlock
            def _send():
                if self._valid_stage(self.next_stage) \
                        and self._valid_micro_batch(mb - 1):
                    cmds.append(SendActivation(send_buf))

            def _recv():
                if self._valid_stage(self.prev_stage) \
                        and self._valid_micro_batch(mb):
                    cmds.append(RecvActivation(recv_buf))

            if _even(self.stage_id):
                _send(), _recv()
            else:
                _recv(), _send()

            if self._valid_micro_batch(mb):
                cmds.append(ForwardPass(recv_buf))
            yield cmds

    def num_pipe_buffers(self):
        return 2


class TrainSchedule(PipeSchedule):
    """1F1B-interleaved training stream. Reference: schedule.py:183-289.

    Total 2*(micro_batches + stages - 1) ticks; each tick maps to a
    (micro_batch, is_forward) pair via the even/odd parity of tick and stage,
    interleaving one forward with one backward in steady state.
    """

    def steps(self):
        prev_mb = -1
        total = 2 * (self.micro_batches + self.stages - 1)
        for step_id in range(total):
            mb, is_forward = self._step_to_micro_batch(step_id)
            cmds = []

            # activation/grad exchange with the neighbor stages
            if is_forward:
                if self._valid_stage(self.prev_stage):
                    if self._valid_micro_batch(mb):
                        cmds.append(RecvActivation(self._buffer_idx(mb)))
                    if self._valid_micro_batch(prev_mb):
                        cmds.append(SendGrad(self._buffer_idx(prev_mb)))
            else:
                if self._valid_stage(self.next_stage):
                    if self._valid_micro_batch(prev_mb):
                        cmds.append(SendActivation(self._buffer_idx(prev_mb)))
                    if self._valid_micro_batch(mb):
                        cmds.append(RecvGrad(self._buffer_idx(mb)))

            if (self.is_first_stage or self.is_last_stage) \
                    and is_forward and self._valid_micro_batch(mb):
                cmds.append(LoadMicroBatch(self._buffer_idx(mb)))

            if self._valid_micro_batch(mb):
                cmds.append(ForwardPass(self._buffer_idx(mb)) if is_forward
                            else BackwardPass(self._buffer_idx(mb)))

            if step_id == total - 1:
                cmds.append(ReduceTiedGrads())
                cmds.append(ReduceGrads())
                cmds.append(OptimizerStep())

            prev_mb = mb
            yield cmds

    def num_pipe_buffers(self):
        """Distance to the last stage bounds in-flight micro-batches
        (reference schedule.py:243)."""
        return max(2, min(self.stages - self.stage_id + 1, self.micro_batches))

    def _step_to_micro_batch(self, step_id):
        """Even ticks run forwards on even stages / backwards on odd stages,
        and vice versa — the phase shift that interleaves 1F1B."""
        base = step_id // 2
        if _even(step_id) == _even(self.stage_id):
            # forward tick for this stage
            if _even(step_id):
                mb = base - self.stage_id // 2
            else:
                mb = (step_id - 1) // 2 - self.stage_id // 2
            return mb, True
        # backward tick
        if _even(step_id):
            mb = base - self.stages + (self.stage_id + 1) // 2
        else:
            mb = (step_id - 1) // 2 - self.stages + 1 + self.stage_id // 2
        return mb, False


class DataParallelSchedule(PipeSchedule):
    """Plain gradient-accumulation DP expressed as a pipe schedule.
    Reference: schedule.py:292-318."""

    def steps(self):
        for step_id in range(self.micro_batches):
            cmds = [LoadMicroBatch(0), ForwardPass(0), BackwardPass(0)]
            if step_id == self.micro_batches - 1:
                cmds.extend([ReduceGrads(), OptimizerStep()])
            yield cmds

    def num_pipe_buffers(self):
        return 1
