"""N-D process topology + parallel grid — the rank-mapping layer.

Reference behavior: deepspeed/runtime/pipe/topology.py:12-455. There, the
topology feeds `dist.new_group` calls; here the same coordinate math instead
describes positions on a named-axis `jax.sharding.Mesh` (parallel/mesh.py) —
"groups" are rank lists used for tests/checkpoint naming, and the Mesh axis
name is the communicator. Axis order is row-major: axes=['pipe','data',
'model'] puts model innermost so TP collectives ride the fastest ICI links
(reference topology.py:246 does the same for NVLink).
"""
import itertools
from collections import namedtuple


class ProcessTopology:
    """Maps n-D cartesian coordinates with named axes to linear ranks
    (row-major). Reference: topology.py:12-219."""

    def __init__(self, axes, dims):
        assert len(axes) == len(dims)
        self.axes = list(axes)
        self.dims = list(dims)
        self.ProcessCoord = namedtuple("ProcessCoord", self.axes)
        self.mapping = {
            self.ProcessCoord(*coord): rank
            for rank, coord in enumerate(
                itertools.product(*[range(d) for d in dims]))
        }
        self._by_rank = {r: c for c, r in self.mapping.items()}

    def get_rank(self, **coords):
        if len(coords) != len(self.axes):
            raise ValueError(
                "get_rank() needs a full coordinate; use filter_match() for slices")
        key = self.ProcessCoord(**coords)
        assert key in self.mapping, f"invalid coordinate {coords}"
        return self.mapping[key]

    def get_axis_names(self):
        return self.axes

    def get_rank_repr(self, rank, omit_axes=("data", "pipe"), inner_sep="_",
                      outer_sep="-"):
        """Checkpoint-name fragment for a rank, e.g. 'model_00'
        (reference topology.py:69-102)."""
        omit = frozenset(omit_axes)
        coord = self.get_coord(rank)
        return outer_sep.join(
            f"{ax}{inner_sep}{getattr(coord, ax):02d}"
            for ax in self.axes if ax not in omit)

    def get_dim(self, axis):
        return self.dims[self.axes.index(axis)] if axis in self.axes else 0

    def get_coord(self, rank):
        if rank not in self._by_rank:
            raise ValueError(f"rank {rank} not in topology")
        return self._by_rank[rank]

    def get_axis_comm_lists(self, axis):
        """All rank lists that vary only along `axis` — the communicator
        groups for that axis (reference topology.py:131-169)."""
        if axis not in self.axes:
            return []
        others = [a for a in self.axes if a != axis]
        lists = []
        for combo in itertools.product(*[range(self.get_dim(a)) for a in others]):
            fixed = dict(zip(others, combo))
            lists.append([self.get_rank(**fixed, **{axis: i})
                          for i in range(self.get_dim(axis))])
        return lists

    def filter_match(self, **criteria):
        """Ranks whose coordinates match all criteria (reference :171-195)."""
        return sorted(
            rank for coord, rank in self.mapping.items()
            if all(getattr(coord, k) == v for k, v in criteria.items()))

    def get_axis_list(self, axis, idx):
        return self.filter_match(**{axis: idx})

    def world_size(self):
        return len(self.mapping)

    def __str__(self):
        return str(self.mapping)


class PipeDataParallelTopology(ProcessTopology):
    """pipe x data: DP innermost so gradient reductions use the
    high-bandwidth links (reference topology.py:235-243)."""

    def __init__(self, num_pp, num_dp):
        super().__init__(axes=["pipe", "data"], dims=[num_pp, num_dp])


class PipeModelDataParallelTopology(ProcessTopology):
    """pipe x data x model: TP innermost (reference topology.py:246-249)."""

    def __init__(self, num_pp, num_mp, num_dp):
        super().__init__(axes=["pipe", "data", "model"],
                         dims=[num_pp, num_dp, num_mp])


class PipelineParallelGrid:
    """Stage/data/model coordinate bookkeeping for one rank + the mpu-style
    interface (reference topology.py:252-455).

    Group-returning methods yield rank lists, not communicator handles: on
    TPU the communicator is the mesh axis itself. `as_mesh_shape()` hands the
    engine the dict that parallel/mesh.py builds a Mesh from.
    """

    def __init__(self, topology=None, process_group=None, rank=0,
                 world_size=None, virtual_stages=1):
        if topology is None:
            assert world_size is not None
            topology = PipeDataParallelTopology(num_pp=1, num_dp=world_size)
        self._topo = topology
        self.global_rank = rank
        self.world_size = topology.world_size()
        # interleaved virtual stages: each physical stage owns
        # ``virtual_stages`` non-contiguous model chunks (Megatron
        # interleaving); chunk q lives on stage q % pipe and is that
        # stage's local chunk q // pipe
        self.virtual_stages = max(1, int(virtual_stages))

        coord = self._topo.get_coord(rank)
        self.stage_id = getattr(coord, "pipe", 0)
        self.data_parallel_id = getattr(coord, "data", 0)
        self.model_parallel_id = getattr(coord, "model", 0)
        self.slice_parallel_id = self.model_parallel_id

        self.pipe_parallel_size = max(1, self._topo.get_dim("pipe"))
        self.data_parallel_size = max(1, self._topo.get_dim("data"))
        self.model_parallel_size = max(1, self._topo.get_dim("model"))

        self.pp_group = self._group_containing("pipe")
        self.dp_group = self._group_containing("data")
        self.slice_group = self._group_containing("model")

        # adjacent-stage p2p pairs incl. wraparound (reference :372-387);
        # on TPU these become the ppermute permutation over the 'pipe' axis
        self.p2p_groups = self._build_p2p_groups()

    def _group_containing(self, axis):
        if self._topo.get_dim(axis) == 0:
            return [self.global_rank]
        for group in self._topo.get_axis_comm_lists(axis):
            if self.global_rank in group:
                return group
        raise AssertionError(f"rank {self.global_rank} in no {axis} group")

    def _build_p2p_groups(self):
        if self._topo.get_dim("pipe") <= 1:
            return []
        pairs = []
        for group in self._topo.get_axis_comm_lists("pipe"):
            for i, rank in enumerate(group):
                pairs.append(sorted([rank, group[(i + 1) % len(group)]]))
        return pairs

    def ppermute_perm(self, reverse=False):
        """(src, dst) stage pairs for lax.ppermute over 'pipe': forward
        shifts activations to the next stage, reverse shifts grads back."""
        n = self.pipe_parallel_size
        if reverse:
            return [(i, (i - 1) % n) for i in range(n)]
        return [(i, (i + 1) % n) for i in range(n)]

    def as_mesh_shape(self):
        return {"pipe": self.pipe_parallel_size,
                "data": self.data_parallel_size,
                "model": self.model_parallel_size}

    # --- virtual-stage (model chunk) coordinates ---------------------------
    @property
    def num_model_chunks(self):
        return self.pipe_parallel_size * self.virtual_stages

    def chunk_owner_stage(self, chunk):
        """Physical stage holding global model chunk ``chunk`` (that
        stage's local chunk index is chunk // pipe)."""
        assert 0 <= chunk < self.num_model_chunks, f"chunk {chunk} invalid"
        return chunk % self.pipe_parallel_size

    # --- stage predicates -------------------------------------------------
    def is_first_stage(self):
        return self.stage_id == 0

    def is_last_stage(self):
        return self.stage_id == self.pipe_parallel_size - 1

    def stage_to_global(self, stage_id, data=None, model=None):
        coords = {"pipe": stage_id,
                  "data": self.data_parallel_id if data is None else data}
        if "model" in self._topo.get_axis_names():
            coords["model"] = self.model_parallel_id if model is None else model
        return self._topo.get_rank(**coords)

    def topology(self):
        return self._topo

    # --- mpu-compatible interface (reference topology.py:398-455) ---------
    def get_global_rank(self):
        return self.global_rank

    def get_pipe_parallel_rank(self):
        return self.stage_id

    def get_pipe_parallel_world_size(self):
        return self.pipe_parallel_size

    def get_pipe_parallel_group(self):
        return self.pp_group

    def get_data_parallel_rank(self):
        return self.data_parallel_id

    def get_data_parallel_world_size(self):
        return self.data_parallel_size

    def get_data_parallel_group(self):
        return self.dp_group

    def get_model_parallel_rank(self):
        return self.model_parallel_id

    def get_model_parallel_world_size(self):
        return self.model_parallel_size

    def get_model_parallel_group(self):
        return self.slice_group

    def get_slice_parallel_rank(self):
        return self.slice_parallel_id

    def get_slice_parallel_world_size(self):
        return self.model_parallel_size

    def get_slice_parallel_group(self):
        return self.slice_group
