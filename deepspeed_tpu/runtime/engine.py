"""DeepSpeedEngine — TPU-native training engine.

API parity with the reference engine (reference: deepspeed/runtime/engine.py:101:
forward :810 / backward :871 / step :1016 / save_checkpoint :1489 /
load_checkpoint :1299), implemented functionally:

- ONE jitted micro-step (value_and_grad + fp32 grad accumulation) and one
  jitted apply-step (overflow check -> lax.cond{skip, update} -> loss-scale
  update), instead of per-parameter backward hooks and bucketed NCCL calls.
- Parallelism is a named-axis Mesh; data parallelism = batch sharded over
  'data' (XLA inserts the psum/reduce_scatter the reference does by hand in
  engine.py:852-868 and zero/stage2.py:740-821).
- ZeRO-1/2 = sharding specs on master weights / optimizer moments / gradient
  accumulator over the 'data' axis (see parallel/mesh.py:zero_partition_spec);
  XLA's SPMD partitioner emits reduce-scatter of grads into the shard and
  all-gather of updated params — the bucket/stream machinery of stage2.py
  disappears (SURVEY §7).
- fp16 master-weight flow: params live in compute dtype (fp16/bf16),
  fp32 master + moments inside the optimizer state (reference
  fp16/fused_optimizer.py:17).
"""
import logging
import os
import pickle
import time
import weakref
from typing import Any, NamedTuple, Optional

import numpy as np

from deepspeed_tpu.parallel import mesh as mesh_lib
from deepspeed_tpu.runtime.config import (ADAFACTOR_OPTIMIZER, ADAM_OPTIMIZER,
                                          ADAMW_OPTIMIZER, DeepSpeedConfig,
                                          LAMB_OPTIMIZER, ONEBIT_ADAM_OPTIMIZER,
                                          SGD_OPTIMIZER,
                                          ZEROONE_ADAM_OPTIMIZER)
from deepspeed_tpu.runtime.constants import ROUTE_TRAIN
from deepspeed_tpu.runtime.dataloader import DeepSpeedDataLoader, RepeatingLoader
from deepspeed_tpu.runtime.fp16.loss_scaler import (LossScaleState,
                                                    make_loss_scale_state,
                                                    update_loss_scale)
from deepspeed_tpu.runtime.lr_schedules import SCHEDULER_REGISTRY
from deepspeed_tpu.runtime.progressive_layer_drop import ProgressiveLayerDrop
from deepspeed_tpu.utils.jax_compat import ensure_compat
from deepspeed_tpu.utils.logging import log_dist, logger
from deepspeed_tpu.utils.timer import SynchronizedWallClockTimer, ThroughputTimer

ensure_compat()

MEMORY_OPT_ALLREDUCE_SIZE = 500000000

FORWARD_MICRO_TIMER = "forward_microstep"
FORWARD_GLOBAL_TIMER = "forward"
BACKWARD_MICRO_TIMER = "backward_microstep"
BACKWARD_GLOBAL_TIMER = "backward"
STEP_MICRO_TIMER = "step_microstep"
STEP_GLOBAL_TIMER = "step"


class TrainState(NamedTuple):
    """Full training state — a single pytree, sharded per config."""
    step: Any             # i32: optimizer steps taken
    micro_step: Any       # i32: micro-batches in current accumulation window
    params: Any           # compute-dtype params (replicated over 'data', TP over 'model')
    opt_state: Any        # optimizer state incl. fp32 master (ZeRO-sharded)
    master: Any           # fp32 master params (None in pure-fp32 mode: params are master)
    accum: Any            # fp32 grad accumulator (ZeRO-2: sharded over 'data')
    scaler: Any           # LossScaleState or None
    skipped_steps: Any    # i32
    rng: Any              # PRNGKey


class DeepSpeedEngine:
    # subclasses whose state layout cannot support the cross-replica
    # integrity vote (ISSUE 13) override this to False — _arm_integrity
    # then arms sentinels-only and DISARM-warns the vote (a class flag,
    # not a name check, so SUBCLASSES inherit the block)
    _integrity_armable = True

    def __init__(self, args=None, model=None, optimizer=None,
                 model_parameters=None, training_data=None, lr_scheduler=None,
                 mpu=None, dist_init_required=None, collate_fn=None,
                 config_params=None, dont_change_device=False):
        import jax

        assert model is not None, "deepspeed_tpu.initialize requires a model"
        self.module = model
        self.client_optimizer = optimizer
        self.client_lr_scheduler = lr_scheduler
        self.training_data = training_data
        self.collate_fn = collate_fn
        self.mpu = mpu
        self.global_steps = 0
        self.micro_steps = 0
        # samples the integrity ladder deliberately skipped (PaLM-style
        # rollback-and-skip, ISSUE 13): biases reshard.data_position so
        # the stream offset stays truthful; persisted with checkpoints
        self.samples_skipped = 0
        self.gradient_average = True
        self.warn_unscaled_loss = True

        if dist_init_required is None or dist_init_required:
            from deepspeed_tpu.utils.distributed import init_distributed

            init_distributed()

        # --- config -------------------------------------------------------
        config_file = getattr(args, "deepspeed_config", None) if args else None
        if config_file is None and args is not None:
            config_file = getattr(args, "deepscale_config", None)
        raw = config_params if config_params is not None else config_file
        assert raw is not None, \
            "DeepSpeed requires --deepspeed_config or config_params"
        if isinstance(raw, str):
            import json

            from deepspeed_tpu.runtime.config_utils import load_config_json

            raw_dict = load_config_json(raw)
        else:
            raw_dict = raw

        # mesh first: the config's world size is the data-parallel degree
        from deepspeed_tpu.runtime.config import get_mesh_shape

        self.mesh = mesh_lib.build_mesh(get_mesh_shape(raw_dict))
        self.dp_world_size = mesh_lib.dp_size(self.mesh)
        self.mp_world_size = mesh_lib.mp_size(self.mesh)
        self.sp_world_size = mesh_lib.sp_size(self.mesh)
        self._config = DeepSpeedConfig(raw_dict, world_size=self.dp_world_size)
        self._config.print_enabled = False

        self.local_dp_size = max(1, self.dp_world_size // jax.process_count())

        # --- precision ----------------------------------------------------
        import jax.numpy as jnp

        if self.fp16_enabled():
            self.compute_dtype = jnp.float16
        elif self.bf16_enabled() or self.amp_enabled():
            self.compute_dtype = jnp.bfloat16
        else:
            self.compute_dtype = jnp.float32
        self.mixed_precision = self.compute_dtype != jnp.float32

        # --- optimizer / scheduler / misc --------------------------------
        self.optimizer = self._configure_basic_optimizer()
        if self.zero_optimization_stage() > 0:
            # reference engine.py:694-700 gates client optimizers through
            # the ZeRO whitelist before partitioning their state
            from deepspeed_tpu.runtime.zero.utils import \
                assert_zero_supported_optimizer

            assert_zero_supported_optimizer(
                self.optimizer, self._config.zero_allow_untested_optimizer)
        self.lr_scheduler = self._configure_lr_scheduler()
        self.progressive_layer_drop = None
        if self.pld_enabled():
            self.progressive_layer_drop = ProgressiveLayerDrop(
                theta=self.pld_theta(), gamma=self.pld_gamma())

        # --- resilience ---------------------------------------------------
        res = self._config.resilience
        self._resilience = res
        self._consecutive_skips = 0
        self._last_ckpt_dir = None
        self._last_metrics = None
        # async checkpoint commit (resilience.async_commit): at most ONE
        # in flight; the background thread owns write+hash+fsync, the
        # training thread owns the rename (+ latest) via
        # _finalize_pending_commit
        self._pending_commit = None
        self._pending_commit_info = None
        self._ckpt_foreground_ms = 0.0
        self._ckpt_metrics = None
        # graceful preemption: the flag is set by request_preemption()
        # (signal-handler safe); the coordinated save + GracefulPreemption
        # raise happen at the next optimizer-step boundary
        self._preempt_requested = False
        self._preempt_poll_enabled = False
        # self-healing supervision (runtime/resilience/supervisor.py):
        # None until a TrainingSupervisor arms its hook points via
        # _arm_supervisor — one is-None check per step boundary
        self._supervisor = None
        self._watchdog = None
        if res.watchdog_enabled:
            from deepspeed_tpu.runtime.resilience.watchdog import \
                TrainingWatchdog

            self._watchdog = TrainingWatchdog(
                max_skipped_steps=res.watchdog_max_skipped_steps,
                max_nan_losses=res.watchdog_max_nan_losses,
                stall_timeout=res.watchdog_stall_timeout,
                default_action=res.watchdog_action)

        # --- telemetry (ISSUE 10) -----------------------------------------
        self._arm_telemetry()

        # --- memory accounting (ISSUE 15) ---------------------------------
        # after telemetry so the measured side can share its lazy compile
        # cache (one lower().compile() per jit serves MFU and memory)
        self._arm_memory_accounting()

        # --- numerical integrity (ISSUE 13) -------------------------------
        # after telemetry so the monitor can claim its tracer lane
        self._arm_integrity()

        self.timers = SynchronizedWallClockTimer()
        self.tput_timer = ThroughputTimer(
            batch_size=self.train_micro_batch_size_per_gpu() * self.dp_world_size,
            num_workers=1, steps_per_output=self.steps_per_print())

        self.training_dataloader = self.deepspeed_io(training_data) \
            if training_data is not None else None

        # --- state (lazy: built on first batch) --------------------------
        self.state: Optional[TrainState] = None
        self._state_shardings = None
        self._jit_micro = None
        self._jit_apply = None
        self._jit_fused = None
        self._jit_eval = None
        self._pending_state = None
        self._train_mode = True
        self._pending_loss = None
        # scheduled stage-3: the staged forward's vjp stash (gathered
        # weights + activations) awaiting its backward
        self._pending_s3_stash = None
        self.summary_writer = None
        if self.tensorboard_enabled() and jax.process_index() == 0:
            from deepspeed_tpu.utils.tb_writer import SummaryWriter

            # real TensorBoard event-file format (reference tensorboardX,
            # engine.py:157-158) — native writer, no tensorboard dep
            self.summary_writer = SummaryWriter(
                log_dir=os.path.join(
                    self.tensorboard_output_path() or ".",
                    self.tensorboard_job_name() or "DeepSpeedJobName"))

        seed = int(raw_dict.get("seed", 42))
        self._init_rng = jax.random.PRNGKey(seed)

        log_dist(
            f"DeepSpeedEngine: mesh={dict(self.mesh.shape)} "
            f"dtype={self.compute_dtype.__name__} zero_stage={self.zero_optimization_stage()} "
            f"micro_batch={self.train_micro_batch_size_per_gpu()} "
            f"gas={self.gradient_accumulation_steps()}", ranks=[0])

    # ------------------------------------------------------------------
    # config getters (parity with reference engine.py:212-406)
    # ------------------------------------------------------------------
    def train_batch_size(self):
        return self._config.train_batch_size

    def train_micro_batch_size_per_gpu(self):
        return self._config.train_micro_batch_size_per_gpu

    def gradient_accumulation_steps(self):
        return self._config.gradient_accumulation_steps

    def steps_per_print(self):
        return self._config.steps_per_print

    def fp16_enabled(self):
        return self._config.fp16_enabled

    def bf16_enabled(self):
        return self._config.bf16_enabled

    def amp_enabled(self):
        return self._config.amp_enabled

    @property
    def _live_state(self):
        """The alive TrainState: between forward() and backward() the micro
        jit has donated self.state's buffers into the staged state, so
        mid-window readers (loss_scale, skipped_steps, eval) must look at
        the staged one.  The scaler/skip counters are identical in both —
        only apply moves them."""
        return self._pending_state if self._pending_state is not None \
            else self.state

    def loss_scale(self):
        if self.state is not None and self.state.scaler is not None:
            # host-synced at most once per optimizer step (the scale only
            # changes in apply): repeated reads — e.g. _report_progress at
            # steps_per_print boundaries plus user polling — must not each
            # pay a device round-trip
            cached = getattr(self, "_scale_cache", None)
            if cached is not None and cached[0] == self.global_steps:
                return cached[1]
            import jax

            val = float(jax.device_get(self._live_state.scaler.loss_scale))
            self._scale_cache = (self.global_steps, val)
            return val
        return self._config.loss_scale or self._config.initial_dynamic_scale

    def dynamic_loss_scale(self):
        return self._config.loss_scale == 0

    def gradient_clipping(self):
        return self._config.gradient_clipping

    def zero_optimization(self):
        return self._config.zero_enabled

    def zero_optimization_stage(self):
        return self._config.zero_optimization_stage

    def zero_cpu_offload(self):
        return self._config.zero_config.cpu_offload

    def zero_reduce_scatter(self):
        return self._config.zero_config.reduce_scatter

    def zero_overlap_comm(self):
        return self._config.zero_config.overlap_comm

    def zero_reduce_bucket_size(self):
        return self._config.zero_config.reduce_bucket_size

    def zero_allgather_bucket_size(self):
        return self._config.zero_config.allgather_bucket_size

    def zero_contiguous_gradients(self):
        return self._config.zero_config.contiguous_gradients

    def zero_elastic_checkpoint(self):
        return self._config.zero_config.elastic_checkpoint

    def zero_load_from_fp32_weights(self):
        return self._config.zero_config.load_from_fp32_weights

    def zero_quantized_gradients(self):
        return self._config.zero_config.quantized_gradients

    def zero_quantized_weights(self):
        return self._config.zero_config.quantized_weights

    def zero_hierarchical_allreduce(self):
        return self._config.zero_config.hierarchical_allreduce

    def allreduce_always_fp32(self):
        return self._config.allreduce_always_fp32

    def prescale_gradients(self):
        return self._config.prescale_gradients

    def gradient_predivide_factor(self):
        return self._config.gradient_predivide_factor

    def sparse_gradients_enabled(self):
        return self._config.sparse_gradients_enabled

    def postscale_gradients(self):
        return not self._config.prescale_gradients

    def wall_clock_breakdown(self):
        return self._config.wall_clock_breakdown

    def memory_breakdown(self):
        return self._config.memory_breakdown

    def tensorboard_enabled(self):
        return self._config.tensorboard_enabled

    def tensorboard_output_path(self):
        return self._config.tensorboard_output_path

    def tensorboard_job_name(self):
        return self._config.tensorboard_job_name

    def optimizer_name(self):
        return self._config.optimizer_name

    def optimizer_params(self):
        return self._config.optimizer_params

    def optimizer_legacy_fusion(self):
        return self._config.optimizer_legacy_fusion

    def scheduler_name(self):
        return self._config.scheduler_name

    def scheduler_params(self):
        return self._config.scheduler_params

    def pld_enabled(self):
        return self._config.pld_enabled

    def pld_theta(self):
        return self._config.pld_theta

    def pld_gamma(self):
        return self._config.pld_gamma

    def elasticity_enabled(self):
        return self._config.elasticity_enabled

    def dump_state(self):
        return self._config.dump_state

    def get_global_grad_norm(self):
        return getattr(self, "_last_grad_norm", None)

    @property
    def skipped_steps(self):
        """Overflow-skipped step count; lives on-device in the train state.
        The device scalar is fetched at most once per optimizer step (the
        counter only moves in apply, which also bumps global_steps) and the
        host value is served from cache after that — the 1-bit freeze probe
        and _report_progress read this repeatedly without extra syncs.
        Checkpoint loads drop the cache explicitly."""
        if self.state is None:
            return 0
        key = (self.global_steps, getattr(self, "_host_skipped", 0))
        cached = getattr(self, "_skipped_cache", None)
        if cached is not None and cached[0] == key:
            return cached[1]
        import jax

        val = int(jax.device_get(self._live_state.skipped_steps)) \
            + getattr(self, "_host_skipped", 0)
        self._skipped_cache = (key, val)
        return val

    def get_lr(self):
        return [self._current_lr()]

    def get_mom(self):
        if self.lr_scheduler is not None and hasattr(self.lr_scheduler, "get_mom"):
            return self.lr_scheduler.get_mom()
        return [getattr(self.optimizer, "beta1", 0.9)]

    # ------------------------------------------------------------------
    # wiring
    # ------------------------------------------------------------------
    def _configure_basic_optimizer(self):
        """Reference analog: engine.py:599-639."""
        if self.client_optimizer is not None:
            return self.client_optimizer
        name = self.optimizer_name()
        params = dict(self.optimizer_params() or {})
        if name is None:
            # default optimizer: Adam (reference requires one; we default sanely)
            name = ADAM_OPTIMIZER
        params.pop("torch_adam", None)
        max_grad_norm = params.pop("max_grad_norm", None)
        if max_grad_norm and not self._config.gradient_clipping:
            self._config.gradient_clipping = max_grad_norm
        if name in (ADAM_OPTIMIZER, ADAMW_OPTIMIZER):
            if self.zero_cpu_offload():
                # ZeRO-Offload: optimizer state + step on the host
                # (reference engine.py:599-614 picks DeepSpeedCPUAdam)
                from deepspeed_tpu.ops.adam.cpu_adam import DeepSpeedCPUAdam

                params.setdefault("adamw_mode", name == ADAMW_OPTIMIZER)
                return DeepSpeedCPUAdam(**params)
            from deepspeed_tpu.ops.adam.fused_adam import FusedAdam

            params.setdefault("adam_w_mode", name == ADAMW_OPTIMIZER)
            return FusedAdam(**params)
        if name == LAMB_OPTIMIZER:
            from deepspeed_tpu.ops.lamb.fused_lamb import FusedLamb

            return FusedLamb(**params)
        if name == ONEBIT_ADAM_OPTIMIZER:
            from deepspeed_tpu.ops.onebit.onebit_adam import OnebitAdam

            # wire compression (reference onebit_adam.py:104-228 compresses
            # BEFORE the network): with data parallelism and no ZeRO/pipe
            # sharding in the way, the train step runs under shard_map over
            # 'data' so gradients stay device-local and the only gradient
            # traffic after freeze_step is the bit-packed collective
            dp = self.dp_world_size
            wire_ok = (params.get("comm_backend_name", "xla") != "none"
                       and dp > 1
                       and self.zero_optimization_stage() == 0
                       and self.mesh.shape.get("pipe", 1) == 1)
            if wire_ok:
                params.setdefault("axis_name", "data")
                params.setdefault("axis_size", dp)
            elif dp > 1:
                # compression silently no-oping would defeat the user's
                # intent — name the blocking condition loudly (VERDICT r4 §6)
                blockers = []
                if self.zero_optimization_stage() != 0:
                    blockers.append(
                        f"zero_optimization.stage={self.zero_optimization_stage()}")
                if self.mesh.shape.get("pipe", 1) != 1:
                    blockers.append(f"pipe={self.mesh.shape.get('pipe')}")
                if params.get("comm_backend_name") == "none":
                    blockers.append("comm_backend_name='none'")
                log_dist(
                    "OneBitAdam: wire compression DISARMED — gradients move "
                    f"dense ({', '.join(blockers)}); the compressed "
                    "collective path requires zero stage 0 and pipe=1",
                    ranks=[0], level=logging.WARNING)
            return OnebitAdam(mesh=self.mesh, **params)
        if name == ZEROONE_ADAM_OPTIMIZER:
            from deepspeed_tpu.ops.onebit.zeroone_adam import ZeroOneAdam

            # 0/1 Adam (arxiv 2202.06009): the 1-bit wire one rung below
            # qgZ.  Armed exactly like the OneBitAdam wire above, plus the
            # stage-3 / CSR / offload blockers — the packed collective
            # owns the whole grad exchange, so anything else claiming the
            # wire disarms it loudly.
            if self._arm_zeroone(params):
                params.setdefault("axis_name", "data")
                params.setdefault("axis_size", self.dp_world_size)
                params.setdefault(
                    "intra_size",
                    self._arm_quantized_allreduce(self.dp_world_size,
                                                  params))
            return ZeroOneAdam(mesh=self.mesh, **params)
        if name == SGD_OPTIMIZER:
            from deepspeed_tpu.ops.adam.sgd import SGD

            return SGD(**params)
        raise ValueError(f"Unknown optimizer type {name!r}")

    def _configure_lr_scheduler(self):
        """Reference analog: engine.py:408-421."""
        if self.client_lr_scheduler is not None:
            return self.client_lr_scheduler
        name = self.scheduler_name()
        if name is None:
            return None
        assert name in SCHEDULER_REGISTRY, f"Unknown scheduler {name}"
        sched = SCHEDULER_REGISTRY[name](**(self.scheduler_params() or {}))
        return sched

    def _current_lr(self):
        if self.lr_scheduler is not None:
            lr = self.lr_scheduler.get_last_lr()[0] \
                if getattr(self.lr_scheduler, "_last_lr", None) else \
                self.lr_scheduler.lr_at(max(0, self.lr_scheduler.last_batch_iteration))
            return float(lr)
        return float(getattr(self.optimizer, "lr", 1e-3))

    def deepspeed_io(self, dataset, batch_size=None, route=ROUTE_TRAIN,
                     pin_memory=False, data_sampler=None, collate_fn=None,
                     num_local_io_workers=None):
        """Reference analog: engine.py:731-772."""
        import jax

        if batch_size is None:
            batch_size = self.train_micro_batch_size_per_gpu() * self.local_dp_size
        return DeepSpeedDataLoader(
            dataset, batch_size=batch_size,
            collate_fn=collate_fn or self.collate_fn,
            num_local_io_workers=num_local_io_workers or 0,
            data_sampler=data_sampler,
            data_parallel_world_size=jax.process_count(),
            data_parallel_rank=jax.process_index(),
            tput_timer=self.tput_timer)

    # ------------------------------------------------------------------
    # state construction
    # ------------------------------------------------------------------
    def _build_shardings(self, params_template):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = self.mesh
        rep = NamedSharding(mesh, P())

        def ns(spec_tree):
            return jax.tree_util.tree_map(
                lambda s: NamedSharding(mesh, s), spec_tree,
                is_leaf=lambda x: isinstance(x, P))

        if hasattr(self.module, "param_partition_spec"):
            tp_spec = self.module.param_partition_spec(params_template)
        else:
            tp_spec = jax.tree_util.tree_map(lambda _: P(), params_template)

        stage = self.zero_optimization_stage()
        dp = self.dp_world_size
        zero_spec = jax.tree_util.tree_map(
            lambda s, l: mesh_lib.zero_merge_spec(s, l, dp) if stage > 0 else s,
            tp_spec, params_template, is_leaf=lambda x: isinstance(x, P))

        # stage 3 (extension; reference engine.py:720-722 caps at 2): the
        # COMPUTE params also live ZeRO-sharded over 'data' — XLA all-gathers
        # each weight at its use sites (fwd and, under remat, again in bwd),
        # exactly stage-3's gather-on-demand, expressed as one spec choice
        param_sh = ns(zero_spec) if stage >= 3 else ns(tp_spec)
        master_sh = ns(zero_spec) if self.mixed_precision else None
        # accum: ZeRO-2+ shards gradients; otherwise keep with param layout
        accum_sh = ns(zero_spec) if stage >= 2 else param_sh

        if self._offload:
            # optimizer state lives on host, gradients stream to it per
            # micro-batch — no device accumulator at all (1x params fp32 of
            # HBM back; the 13B-per-chip headline depends on it). Micro-step
            # grads come out ZeRO-sharded: out_shardings below makes XLA
            # reduce-scatter instead of all-reduce, and each process then
            # fetches only its own shard (reference stage2.py:876-958
            # updates only the local partition).
            # sparse_gradients (reference engine.py:187-193,1227-1265):
            # models may declare untied embedding tables whose gradients are
            # row-sparse; those leaves stream to the host as (row indices,
            # row values) with capacity = batch tokens instead of the dense
            # table, cutting offload D2H traffic by ~vocab/tokens. The flag
            # tree is static (model contract); row capacity binds per trace.
            self._offload_sparse_flags = None
            if self.sparse_gradients_enabled() \
                    and hasattr(self.module, "sparse_grad_spec"):
                self._offload_sparse_flags = \
                    self.module.sparse_grad_spec(params_template)
            zero_ns = ns(zero_spec)
            if self._offload_sparse_flags is not None:
                # grads out_shardings: sparse leaves become replicated
                # {indices, values} pairs; region layout (for the host
                # master/moment step) treats them as whole-buffer regions
                self._offload_grad_sh = jax.tree_util.tree_map(
                    lambda flag, s: {"csr_indices": rep, "csr_values": rep,
                                     "csr_dropped": rep}
                    if flag else s,
                    self._offload_sparse_flags, zero_ns)
                self._offload_region_sh = jax.tree_util.tree_map(
                    lambda flag, s: rep if flag else s,
                    self._offload_sparse_flags, zero_ns)
            else:
                self._offload_grad_sh = zero_ns
                self._offload_region_sh = zero_ns
            self._shardings = TrainState(
                step=rep, micro_step=rep, params=param_sh, opt_state=(),
                master=None, accum=(),
                scaler=(LossScaleState(rep, rep, rep, rep)
                        if self._use_loss_scaler() else None),
                skipped_steps=rep, rng=rep)
            self._batch_sharding_cache = {}
            self._arm_stage3(stage, dp, params_template)
            self._arm_quantized_collectives(stage, dp)
            return self._shardings
        # sparse_gradients under plain DP (reference engine.py:1227-1265
        # swaps the embedding-grad all-reduce for a sparse all-gather): the
        # micro step's gradient exchange runs under shard_map with 'data'
        # manual, flagged leaves move as (row indices, row values) at
        # capacity = local lookup tokens instead of the dense (vocab, dim)
        # table. Armed only where the dense accumulator layout survives:
        # stage <= 1 (stage 2 shards accum over 'data'), no pipe/seq axes.
        self._csr_dp_flags = None
        if (self.sparse_gradients_enabled()
                and hasattr(self.module, "sparse_grad_spec")
                and dp > 1 and stage <= 1
                and self.mesh.shape.get("pipe", 1) == 1
                and self.sp_world_size == 1):
            self._csr_dp_flags = self.module.sparse_grad_spec(params_template)
        opt_state_template = jax.eval_shape(self.optimizer.init_state, params_template)
        flat_opt, opt_def = jax.tree_util.tree_flatten(opt_state_template)
        if hasattr(self.optimizer, "state_spec"):
            # optimizer declares its state layout in terms of param specs
            # (None = replicated scalar) — exact per-param mapping
            spec_tree = self.optimizer.state_spec(zero_spec)
            spec_flat = jax.tree_util.tree_flatten(
                spec_tree, is_leaf=lambda x: x is None or isinstance(x, P))[0]
            assert len(spec_flat) == len(flat_opt), \
                f"optimizer state_spec leaves ({len(spec_flat)}) != state " \
                f"leaves ({len(flat_opt)})"
            opt_sh_flat = [rep if s is None else NamedSharding(mesh, s)
                           for s in spec_flat]
        else:
            from deepspeed_tpu.runtime.utils import opt_shardings_by_shape

            flat_param_sh = jax.tree_util.tree_leaves(ns(zero_spec))
            param_shapes = [tuple(l.shape)
                            for l in jax.tree_util.tree_leaves(params_template)]
            opt_sh_flat = opt_shardings_by_shape(
                flat_opt, param_shapes, flat_param_sh, rep)
        opt_sh = opt_def.unflatten(opt_sh_flat)

        self._shardings = TrainState(
            step=rep, micro_step=rep, params=param_sh, opt_state=opt_sh,
            master=master_sh, accum=accum_sh,
            scaler=(LossScaleState(rep, rep, rep, rep)
                    if self._use_loss_scaler() else None),
            skipped_steps=rep, rng=rep)
        self._batch_sharding_cache = {}
        self._arm_stage3(stage, dp, params_template)
        self._arm_quantized_collectives(stage, dp)
        return self._shardings

    def _arm_stage3(self, stage, dp, params_template):
        """Decide whether stage 3 runs the SCHEDULED gather path (ISSUE 8):
        a compile-time per-layer-block plan (runtime/zero/stage3.py) of
        quantized (int8 + fp32 scales) all-gathers, one per partitioned
        leaf per micro-step, with the gathered weight persisted fwd->bwd
        as a vjp residual and donated/freed at wgrad.  Disarmed, stage 3
        falls back to the implicit path — XLA inserts full-precision
        gathers at every use site (and again in a remat'd backward) —
        with every blocker named loudly (the qgZ/OneBit discipline)."""
        import warnings

        import jax
        from jax.sharding import NamedSharding

        from deepspeed_tpu.runtime.zero import stage3 as s3

        zc = self._config.zero_config
        self._s3_sched_armed = False
        self._s3_plan = None
        if stage != 3:
            return
        dims_tree = jax.tree_util.tree_map(
            _spec_data_dim, self._shardings.params,
            is_leaf=lambda x: isinstance(x, NamedSharding))
        dims = jax.tree_util.tree_leaves(dims_tree,
                                         is_leaf=lambda x: x is None)
        names = _leaf_path_names(params_template)
        shapes = [tuple(l.shape)
                  for l in jax.tree_util.tree_leaves(params_template)]
        plan = s3.build_gather_plan(
            names, shapes, dims, dp,
            block_size=zc.quantization_block_size,
            param_dtype=str(np.dtype(self.compute_dtype)))
        self._s3_plan = plan
        self._s3_dims = dims_tree
        blockers = []
        if not zc.stage3_scheduled_gathers:
            blockers.append("zero_optimization.stage3_scheduled_gathers="
                            "false")
        if dp <= 1:
            blockers.append("data-parallel degree is 1 (nothing is "
                            "partitioned)")
        if self._offload:
            blockers.append("cpu_offload=true (params materialize through "
                            "the offload push, which has its own qwZ wire)")
        if self.mesh.shape.get("pipe", 1) != 1:
            blockers.append(f"pipe={self.mesh.shape.get('pipe')}")
        if self.sp_world_size != 1:
            blockers.append(f"seq={self.sp_world_size}")
        if not blockers and plan.n_gathered_leaves == 0:
            blockers.append("no parameter leaf is partitionable over "
                            "'data' (all too small/indivisible)")
        budget = zc.stage3_prefetch_budget
        if not blockers and not plan.within_budget(budget):
            blockers.append(
                f"gather plan needs {plan.gathered_bytes} B of gathered "
                f"weights live fwd->bwd, over stage3_prefetch_budget="
                f"{budget} B — raise the budget or accept the implicit "
                f"path's per-use gathers")
        if blockers:
            log_dist(
                "ZeRO stage-3: scheduled quantized gathers DISARMED — "
                f"falling back to XLA-implicit per-use all-gathers "
                f"({'; '.join(blockers)})", ranks=[0],
                level=logging.WARNING)
            return
        self._s3_sched_armed = True
        # the bwd jit donates the stash; gathered-weight residuals are
        # donor-only (they alias no output), which XLA reports once per
        # compile with this warning — expected, same as the zb-h1 stash
        warnings.filterwarnings(
            "ignore", message="Some donated buffers were not usable")
        log_dist(
            f"ZeRO stage-3: scheduled quantized gathers armed — "
            f"{plan.n_gathered_leaves} leaves in {len(plan.blocks)} "
            f"layer blocks, {plan.wire_bytes_per_gather} B int8+scales "
            f"wire per gather, {plan.gathered_bytes} B gathered peak "
            f"(budget {budget or 'unbounded'})", ranks=[0])

    def stage3_report(self):
        """The compile-time gather plan's report (blocks, per-block bytes,
        peak gathered footprint) plus arming status — the numbers
        stage3_prefetch_budget is sized from.  None below stage 3 or
        before state build."""
        if getattr(self, "_s3_plan", None) is None:
            return None
        report = self._s3_plan.report()
        report["armed"] = bool(self._s3_sched_armed)
        report["prefetch_budget"] = \
            self._config.zero_config.stage3_prefetch_budget
        return report

    def _make_stage3_gather(self):
        """params(sharded) -> params(replicated) through the plan's
        quantized all-gathers, emitted in forward block order so XLA's
        latency-hiding scheduler prefetches block k+1's gather behind
        block k's compute.  Straight-through vjp: gradients flow back
        constrained onto the ZeRO shard (one reduce-scatter per leaf)."""
        import jax

        from deepspeed_tpu.runtime.custom_collectives import \
            quantized_all_gather

        dims = self._s3_dims
        mesh = self.mesh
        block = self._config.zero_config.quantization_block_size

        def gather(params):
            def one(dim, p):
                if dim is None:
                    return p
                return quantized_all_gather(
                    p, mesh, dim=dim, block_size=block,
                    out_dtype=p.dtype)

            return jax.tree_util.tree_map(one, dims, params,
                                          is_leaf=lambda x: x is None)

        return gather

    def _make_stage3_fwd(self):
        """Forward half of the staged stage-3 micro step: gather once,
        compute the loss, and return the vjp closure (a tree_util.Partial
        whose residuals INCLUDE the gathered weights) as the stash that
        crosses to the backward jit — the PR-6 ZB stash idiom.  The
        engine state is NOT donated here: it stays alive until backward
        commits it."""
        import jax
        import jax.numpy as jnp

        gas = self.gradient_accumulation_steps()
        model = self.module
        gather = self._make_stage3_gather()

        def s3_fwd(state: TrainState, batch):
            rng = jax.random.fold_in(state.rng,
                                     state.micro_step + state.step * 131071)
            scale = state.scaler.loss_scale if state.scaler is not None \
                else jnp.float32(1.0)

            def loss_fn(shards):
                full = gather(shards)
                loss, _ = model.loss(full, batch, rng, train=True)
                return loss.astype(jnp.float32) * scale / gas, loss

            _, vjp, loss = jax.vjp(loss_fn, state.params, has_aux=True)
            return loss, vjp

        return s3_fwd

    def _make_stage3_bwd(self):
        """Backward half: evaluate the stash into gradients (they arrive
        ZeRO-sharded through the gather's straight-through cotangent
        constraint — the accumulator add is collective-free) and commit
        the micro step.  Donates BOTH the state (in-place accum) and the
        stash, so the gathered weights free at wgrad instead of
        surviving to peak memory."""
        import jax
        import jax.numpy as jnp

        def s3_bwd(state: TrainState, stash):
            grads, = stash(jnp.float32(1.0))
            accum = jax.tree_util.tree_map(
                lambda a, g: a + g.astype(jnp.float32), state.accum, grads)
            return state._replace(accum=accum,
                                  micro_step=state.micro_step + 1)

        return s3_bwd

    def _arm_quantized_collectives(self, stage, dp):
        """Decide whether the ZeRO++-style quantized collectives run
        (qgZ: int8 gradient reduce-scatter; qwZ: int8 offload param
        all-gather) and resolve the hierarchical intra-group size.  Asked-for
        compression silently no-oping would defeat the user's intent, so
        every blocker is named loudly (same discipline as the OneBitAdam
        wire arming above)."""
        import math

        import jax

        zc = self._config.zero_config
        self._qgz_armed = False
        self._qgz_intra = 0
        self._qwz_armed = False
        if zc.quantized_gradients:
            blockers = []
            if dp <= 1:
                blockers.append("data-parallel degree is 1")
            if stage != 2:
                blockers.append(
                    f"zero_optimization.stage={stage} (qgZ quantizes the "
                    f"stage-2 sharded-accumulator reduce-scatter)")
            if self._offload:
                blockers.append("cpu_offload=true (gradients stream D2H, "
                                "no collective to quantize)")
            if getattr(self, "_csr_dp_flags", None) is not None:
                blockers.append("sparse_gradients CSR exchange is armed")
            if self.mesh.shape.get("pipe", 1) != 1:
                blockers.append(f"pipe={self.mesh.shape.get('pipe')}")
            if self.sp_world_size != 1:
                blockers.append(f"seq={self.sp_world_size}")
            if blockers:
                log_dist(
                    "ZeRO qgZ: quantized_gradients DISARMED — gradients "
                    f"move dense ({', '.join(blockers)}); the quantized "
                    "reduce-scatter requires zero stage 2, no cpu_offload, "
                    "and pipe=seq=1", ranks=[0], level=logging.WARNING)
            else:
                self._qgz_armed = True
        if zc.quantized_weights:
            if self._offload and dp > 1:
                self._qwz_armed = True
            elif getattr(self, "_s3_sched_armed", False):
                # stage-3's scheduled gathers ARE the int8 weight wire:
                # the ask is satisfied, nothing to disarm
                log_dist(
                    "ZeRO qwZ: quantized_weights rides the stage-3 "
                    "scheduled gather plan (int8 blocks + fp32 scales per "
                    "micro-step)", ranks=[0])
            else:
                blocker = "cpu_offload=false (the int8 weight gather rides " \
                          "the offload parameter push or the stage-3 " \
                          "scheduled plan)" \
                    if not self._offload else "data-parallel degree is 1"
                log_dist(
                    f"ZeRO qwZ: quantized_weights DISARMED — parameters "
                    f"move in the compute dtype ({blocker})",
                    ranks=[0], level=logging.WARNING)
        if zc.hierarchical_allreduce and self._qgz_armed:
            k = zc.hierarchical_intra_size
            auto = k <= 0
            if auto:
                # auto: co-located ranks (consecutive on the 'data' axis)
                # form the intra group
                k = math.gcd(dp, jax.local_device_count())
            if 1 < k < dp and dp % k == 0:
                self._qgz_intra = k
            elif not auto:
                log_dist(
                    f"ZeRO qgZ: hierarchical_allreduce requested but "
                    f"hierarchical_intra_size={k} cannot form >=2 groups "
                    f"over the data axis ({dp}; needs 1 < k < {dp} with k "
                    f"dividing it); using the flat quantized all_to_all",
                    ranks=[0], level=logging.WARNING)
            # auto + degenerate (e.g. single host: every rank is intra)
            # falls back flat silently — nothing was misconfigured
        elif zc.hierarchical_allreduce:
            # the knob shapes the QUANTIZED exchange only — say so instead
            # of silently ignoring it
            why = "quantized_gradients is disarmed (see warning above)" \
                if zc.quantized_gradients else \
                "zero_optimization.quantized_gradients is not enabled"
            log_dist(
                f"ZeRO qgZ: hierarchical_allreduce has no effect — it "
                f"routes the quantized gradient exchange and {why}",
                ranks=[0], level=logging.WARNING)

    def _arm_zeroone(self, params):
        """Decide whether 0/1 Adam runs the packed 1-bit wire (the fused
        step under shard_map with 'data' manual, sync rounds moving only
        sign bits + per-block scales).  Asked-for compression silently
        no-oping would defeat the user's intent, so every blocker is
        named loudly — a disarmed ZeroOneAdam falls back to the generic
        optimizer path: dense (bias-correction-free) Adam whose variance
        never freezes and whose local rounds never skip."""
        dp = self.dp_world_size
        self._zeroone_armed = False
        blockers = []
        if params.get("comm_backend_name", "xla") == "none":
            blockers.append("comm_backend_name='none'")
        if dp <= 1:
            blockers.append("data-parallel degree is 1")
        if self.zero_optimization_stage() != 0:
            blockers.append(
                f"zero_optimization.stage={self.zero_optimization_stage()} "
                f"(stage >= 1 shards the accumulator; stage-3 scheduled "
                f"gathers own the parameter wire)")
        if self.mesh.shape.get("pipe", 1) != 1:
            blockers.append(f"pipe={self.mesh.shape.get('pipe')}")
        if self.zero_cpu_offload():
            blockers.append("cpu_offload=true (gradients stream D2H, no "
                            "collective to compress)")
        if self.sparse_gradients_enabled():
            blockers.append("sparse_gradients CSR exchange owns the "
                            "embedding-grad wire")
        if blockers:
            log_dist(
                "ZeroOneAdam: wire compression DISARMED — gradients move "
                f"dense and the variance never freezes "
                f"({', '.join(blockers)}); the 1-bit collective path "
                "requires dp>1, zero stage 0, pipe=1, no cpu_offload and "
                "no sparse_gradients",
                ranks=[0], level=logging.WARNING)
            return False
        self._zeroone_armed = True
        return True

    def _arm_quantized_allreduce(self, dp, params=None):
        """Resolve the quantized_all_reduce wire shape for the armed 0/1
        Adam path: flat vs hierarchical two-hop (the qgZ
        ``axis_index_groups`` machinery).  Returns the intra-group size
        (0 = flat) and records it for the comm accounting."""
        import math

        import jax

        params = params or {}
        zc = self._config.zero_config
        self._qar_armed = False
        self._qar_intra = 0
        if dp <= 1:
            log_dist(
                "quantized_all_reduce: DISARMED — data-parallel degree is "
                "1, the collective collapses to the local "
                "quantize/dequantize twin (no wire to shrink)",
                ranks=[0], level=logging.WARNING)
            return 0
        self._qar_armed = True
        k = int(params.get("intra_size", 0) or 0)
        if not k and zc.hierarchical_allreduce:
            k = zc.hierarchical_intra_size
            if k <= 0:
                # auto: co-located ranks (consecutive on the 'data' axis)
                # form the intra group, as for qgZ
                k = math.gcd(dp, jax.local_device_count())
        if 1 < k < dp and dp % k == 0:
            self._qar_intra = k
        elif k > 1:
            log_dist(
                f"quantized_all_reduce: hierarchical intra size {k} cannot "
                f"form >=2 groups over the data axis ({dp}; needs 1 < k < "
                f"{dp} with k dividing it); using the flat wire",
                ranks=[0], level=logging.WARNING)
        return self._qar_intra

    # ------------------------------------------------------------------
    # telemetry (deepspeed_tpu/telemetry/, ISSUE 10)
    # ------------------------------------------------------------------
    def _arm_telemetry(self):
        """Build the telemetry session (span tracer + metrics registry/
        stream + MFU accounting) when the ``telemetry`` config block asks
        for it.  Disarmed engines hold ``self._tracer = None`` — every
        instrumentation site is one attribute check, tracing is purely
        host-side, and the compiled programs are UNTOUCHED either way
        (bit-identical steps, zero extra compiles; pinned by tier-1
        tests).  Sub-knobs set while the master switch is off would
        silently observe nothing, so that DISARMED state warns loudly
        (the OneBitAdam/qgZ discipline)."""
        from deepspeed_tpu.runtime.constants import (
            TELEMETRY_ENABLED, TELEMETRY_METRICS_FSYNC,
            TELEMETRY_METRICS_JSONL, TELEMETRY_MFU, TELEMETRY_PEAK_TFLOPS,
            TELEMETRY_TRACE, TELEMETRY_TRACE_CAPACITY)

        tc = self._config.telemetry
        # the compiled-program registry is ALWAYS on: registration is a
        # shape capture + dict insert once per jit (no compile, no device
        # work), and it is the seam tools/graftlint/program_lint.py and
        # ROADMAP item 5's plan compiler read — telemetry arming only
        # gates the FLOP/memory ledgers below
        from deepspeed_tpu.telemetry import ProgramRegistry

        self._programs = ProgramRegistry("base")
        self._telemetry = None
        self._tracer = None
        self._chaos_observer = None
        self._lane_train = 0
        self._lane_ckpt = 0
        self._mfu_n_params = None
        self._mfu_tokens_per_step = None
        if not tc[TELEMETRY_ENABLED]:
            if tc[TELEMETRY_METRICS_JSONL]:
                log_dist(
                    "telemetry: DISARMED — telemetry.metrics_jsonl is set "
                    "but telemetry.enabled=false, so no trace, step stream "
                    "or MFU accounting will be produced; set "
                    "telemetry.enabled=true to arm it",
                    ranks=[0], level=logging.WARNING)
            return
        from deepspeed_tpu.telemetry import Telemetry

        self._telemetry = Telemetry(
            trace=tc[TELEMETRY_TRACE],
            trace_capacity=tc[TELEMETRY_TRACE_CAPACITY],
            metrics_jsonl=tc[TELEMETRY_METRICS_JSONL],
            metrics_fsync=tc[TELEMETRY_METRICS_FSYNC],
            mfu=tc[TELEMETRY_MFU],
            peak_tflops_per_device=tc[TELEMETRY_PEAK_TFLOPS])
        tr = self._telemetry.tracer
        self._tracer = tr
        if tr is not None:
            self._lane_train = tr.lane("train")
            self._lane_ckpt = tr.lane("ckpt")
            tr.intern("optimizer_step", args=("global_step",))
            tr.intern("overflow_skip", args=("global_step",))
            tr.intern("preempt", args=("global_step",))
            if self._watchdog is not None:
                # observe-only callback: returns None so the verdict
                # stays with the configured callbacks/default action
                self._watchdog.add_callback(self._telemetry_watchdog_cb)
            from deepspeed_tpu.runtime.resilience import chaos

            # the chaos observer list is PROCESS-GLOBAL: register a
            # weakref trampoline, not a bound method, so an abandoned
            # engine (bench ladders build one per attempt) stays
            # collectable and its __del__ can deregister cleanly
            ref = weakref.ref(self)

            def _chaos_obs(kind, detail=None):
                eng = ref()
                if eng is not None:
                    eng._telemetry_chaos_cb(kind, detail)

            self._chaos_observer = chaos.add_observer(_chaos_obs)
        log_dist(
            f"telemetry armed: trace={tc[TELEMETRY_TRACE]} "
            f"(capacity {tc[TELEMETRY_TRACE_CAPACITY]}), "
            f"metrics_jsonl={tc[TELEMETRY_METRICS_JSONL] or 'off'}, "
            f"mfu={tc[TELEMETRY_MFU]}", ranks=[0])

    def _telemetry_watchdog_cb(self, event):
        tr = self._tracer
        if tr is not None:
            tr.instant(f"watchdog_{event.kind}", self._lane_train,
                       a0=int(event.step))
        return None

    def _telemetry_chaos_cb(self, kind, detail=None):
        tr = self._tracer
        if tr is not None:
            tr.instant(f"chaos_{kind}", self._lane_train)

    def close_telemetry(self):
        """Release the telemetry session's process-global hooks (the
        chaos observer) and close the metrics-stream file handle.
        Idempotent; also runs at GC so loops that build many engines
        (bench ladders) never accumulate observers or leak JSONL fds.
        The session object stays readable — only the stream is closed."""
        obs = getattr(self, "_chaos_observer", None)
        if obs is not None:
            self._chaos_observer = None
            from deepspeed_tpu.runtime.resilience import chaos

            chaos.remove_observer(obs)
        tel = getattr(self, "_telemetry", None)
        if tel is not None:
            tel.close()

    def __del__(self):
        try:
            self.close_telemetry()
        except Exception:  # lint: allow-broad-except — interpreter
            # teardown can fail imports mid-GC; never raise from __del__
            pass

    @property
    def telemetry(self):
        """The armed Telemetry session, or None."""
        return self._telemetry

    def export_trace(self, path, complete_events=True):
        """Write the retained trace as Chrome-trace-event JSON (loadable
        in chrome://tracing / Perfetto); None when tracing is disarmed."""
        tr = self._tracer
        if tr is None:
            return None
        return tr.export_chrome_trace(path, complete_events=complete_events)

    @property
    def program_registry(self):
        """The engine's compiled-program registry (always armed): every
        jit the engine has dispatched, with its declarative HLO contract.
        Read by ``python -m tools.graftlint --programs``."""
        return self._programs

    def _register_program(self, name, jit_fn, args, contract=None,
                          calls_per_step=1.0):
        """Register one jit with the always-on program registry (shape
        capture + dict insert; the lower().compile() is lazy and happens
        only when a lint/report pass reads the entry)."""
        from deepspeed_tpu.telemetry import register_program

        register_program(self._programs, name, jit_fn, args,
                         mesh=self.mesh, contract=contract,
                         calls_per_step=calls_per_step)

    def _register_mfu_jit(self, name, jit_fn, args, calls_per_step=1.0,
                          mem_label=None, program_name=None, contract=None):
        """Capture-by-shape registration of a dispatched jit with the MFU
        ledger AND the measured-memory ledger: a ShapeDtypeStruct tree of
        the REAL dispatch args is taken once (first dispatch; donated
        buffers still alive) and the lower+compile+cost/memory_analysis
        runs lazily at report time — never on the step path, never inside
        a recompile-guard window.  The two ledgers share one compiled
        object per name (``MemoryAccounting(shared=...)``), so arming
        both costs ONE compile per jit.  ``mem_label`` additionally arms
        the analytic-vs-measured transient cross-check for jits the
        engine makes a budget claim about.  The program registry is fed
        FIRST and unconditionally (``program_name`` names the program
        when one MFU slot covers several compiled variants, e.g. the 0/1
        Adam per-(phase, k) fused programs; ``contract`` declares the
        entry's HLO contract for tools/graftlint/program_lint.py)."""
        self._register_program(program_name or name, jit_fn, args,
                               contract=contract,
                               calls_per_step=calls_per_step)
        tel = self._telemetry
        if tel is None:
            return
        from deepspeed_tpu.telemetry import register_by_shape

        register_by_shape(tel.mfu, name, jit_fn, args, mesh=self.mesh,
                          calls_per_step=calls_per_step)
        if self._memacct is not None:
            from deepspeed_tpu.runtime import memory_accounting as mem_acc

            mem_acc.register_by_shape(
                self._memacct, name, jit_fn, args, mesh=self.mesh,
                calls_per_step=calls_per_step, expect_label=mem_label)

    def _note_mfu_workload(self, batch, micros_in_batch=1):
        """Record the 6ND inputs once: parameter count (from the live
        state) and tokens per optimizer step (largest integer leaf of the
        dispatched batch × the accumulation factor not already in its
        shape)."""
        if self._telemetry is None or self._mfu_tokens_per_step is not None:
            return
        import jax

        if self.state is not None:
            self._mfu_n_params = sum(
                int(l.size)
                for l in jax.tree_util.tree_leaves(self.state.params))
        tokens = 0
        for leaf in jax.tree_util.tree_leaves(batch):
            dt = getattr(leaf, "dtype", None)
            if dt is not None and np.issubdtype(np.dtype(dt), np.integer):
                tokens = max(tokens, int(np.prod(np.shape(leaf))))
        if tokens:
            self._mfu_tokens_per_step = tokens * max(1, micros_in_batch)

    def _mfu_report(self):
        tel = self._telemetry
        from deepspeed_tpu.telemetry import model_flops_per_step

        devs = self.mesh.devices.reshape(-1)
        model_flops = None
        if self._mfu_n_params and self._mfu_tokens_per_step:
            model_flops = model_flops_per_step(self._mfu_n_params,
                                               self._mfu_tokens_per_step)
        rep = tel.mfu.report(
            step_time_s=tel.step_time_s(), n_devices=int(len(devs)),
            model_flops=model_flops,
            device_kind=getattr(devs[0], "device_kind", None))
        rep["n_params"] = self._mfu_n_params
        rep["tokens_per_step"] = self._mfu_tokens_per_step
        return rep

    def telemetry_report(self):
        """ONE observability report: consolidates the legacy builders —
        ``_last_metrics`` (per-step scalars), ``comm_volume_report()``
        (analytic wire bytes), and on subclasses ``pipeline_report()`` /
        ``serving_report()`` — behind a single dict WITHOUT replacing
        them, plus the telemetry-only sections: the metrics-registry
        snapshot, the trace summary, and the measured-vs-analytic
        MFU/HFU ledger (``mfu``, populated from
        ``compiled.cost_analysis()``)."""
        report = {
            "engine": type(self).__name__,
            "global_steps": self.global_steps,
            "telemetry_armed": self._telemetry is not None,
            "last_metrics": dict(self._last_metrics)
            if isinstance(self._last_metrics, dict) else self._last_metrics,
        }
        if self.state is not None:
            report["comm"] = self.comm_volume_report()
        if self._supervisor is not None:
            # recovery accounting (ISSUE 12): incident ledger, MTTR,
            # downtime spans, goodput-samples-per-wall-step
            report["recovery"] = self._supervisor.report()
        if self._integrity is not None:
            # numerical-integrity accounting (ISSUE 13): anomaly/vote
            # ledger, detection latency, false-positive counters
            report["integrity"] = self._integrity.report()
        # memory leg (ISSUE 15): analytic components always; measured
        # per-jit memory_analysis + device watermarks when armed
        report["memory"] = self.memory_report()
        tel = self._telemetry
        if tel is None:
            return report
        report["metrics"] = tel.registry.snapshot()
        if tel.tracer is not None:
            report["trace"] = tel.tracer.summary()
        if tel.mfu is not None:
            report["mfu"] = self._mfu_report()
        return report

    # ------------------------------------------------------------------
    # memory accounting (runtime/memory_accounting.py, ISSUE 15)
    # ------------------------------------------------------------------
    def _arm_memory_accounting(self):
        """Arm the measured side of the HBM accounting when telemetry is
        on: every step jit registers capture-by-shape with a
        :class:`runtime.memory_accounting.MemoryAccounting` whose
        ``memory_analysis()`` reads run lazily at report time, sharing
        the MFU channel's compile cache (one compile per jit, zero on
        the step path, zero for a disarmed engine — the compiled
        programs are untouched either way).  The analytic component
        model in ``memory_report()`` works armed or not; with
        ``telemetry.enabled`` on but ``telemetry.memory`` off the
        measured side is DISARMED with a loud warning, because budgets
        sized from the analytic model alone are exactly the unchecked
        estimates this channel exists to catch."""
        from deepspeed_tpu.runtime.constants import (TELEMETRY_ENABLED,
                                                     TELEMETRY_MEMORY)

        tc = self._config.telemetry
        self._memacct = None
        self._mem_stats_available = None   # None = probe on first step
        self._lane_mem = 0
        if not tc[TELEMETRY_ENABLED]:
            return
        if not tc[TELEMETRY_MEMORY]:
            log_dist(
                "memory accounting: DISARMED — telemetry.memory=false; "
                "memory_report() will carry the analytic component model "
                "only, with no measured memory_analysis() cross-check and "
                "no per-step HBM gauges", ranks=[0],
                level=logging.WARNING)
            return
        from deepspeed_tpu.runtime import memory_accounting as mem_acc

        self._memacct = mem_acc.MemoryAccounting(
            shared=self._telemetry.mfu if self._telemetry else None)
        if self._tracer is not None:
            self._lane_mem = self._tracer.lane("mem")
            self._tracer.intern("hbm_in_use", args=("bytes", "peak"))

    def _analytic_memory_components(self):
        """Analytic per-device HBM bytes of the live train state, by
        component — EXACT shard shapes (each leaf's ``shard_shape`` under
        its real sharding), not a modeled partition factor.  None before
        the first batch builds the state."""
        if self.state is None:
            return None
        from deepspeed_tpu.runtime import memory_accounting as mem_acc

        state = self.state
        components = {
            "params_bytes": mem_acc.tree_device_bytes(state.params),
            "grad_accum_bytes": mem_acc.tree_device_bytes(state.accum),
            "master_bytes": mem_acc.tree_device_bytes(state.master),
            "optimizer_state_bytes":
                mem_acc.tree_device_bytes(state.opt_state),
            "scaler_bytes": mem_acc.tree_device_bytes(state.scaler),
        }
        zc = self._config.zero_config
        transient = {
            # scheduled stage-3: gathered weights persist fwd->bwd as
            # vjp residuals — the plan's peak is live on top of the
            # sharded-at-rest state (the stage3_prefetch_budget number)
            "gathered_stage3_bytes":
                self._s3_plan.gathered_bytes
                if getattr(self, "_s3_sched_armed", False) else 0,
            "quantization_scratch_bytes": 0,
        }
        if getattr(self, "_qgz_armed", False):
            leaves, _ = self._comm_leaf_specs()
            transient["quantization_scratch_bytes"] = \
                mem_acc.quantization_scratch_bytes(
                    leaves, self.dp_world_size,
                    zc.quantization_block_size)
        persistent = sum(components.values())
        transient_total = sum(transient.values())
        return {
            "components": components,
            "transient": transient,
            "persistent_bytes": persistent,
            "transient_bytes": transient_total,
            "peak_bytes": persistent + transient_total,
        }

    def memory_report(self):
        """The memory leg of the accounting trio: analytic per-component
        state bytes (exact shard shapes), measured per-jit
        ``memory_analysis()`` with analytic-vs-measured deltas and the
        arming-time cross-checks, and the per-device ``memory_stats()``
        watermark + headroom where the backend reports one.  Cold
        report builder — first call compiles each registered jit's
        shape-struct lowering (shared with the MFU ledger)."""
        from deepspeed_tpu.runtime import memory_accounting as mem_acc

        return mem_acc.memory_report(
            analytic=self._analytic_memory_components(),
            accounting=self._memacct,
            devices=list(self.mesh.devices.reshape(-1)),
            extra={"engine": type(self).__name__})

    def _memory_step_gauges(self):
        """Per-step ``mem`` gauges: HBM in-use/peak from
        ``memory_stats()`` where the backend reports it.  The first step
        probes ONE device; backends with no stats (CPU) disable the path
        for the rest of the run, so the steady-state cost on an
        unsupported backend is a single attribute check."""
        if self._memacct is None or self._mem_stats_available is False:
            return
        from deepspeed_tpu.runtime import memory_accounting as mem_acc

        devices = self.mesh.devices.reshape(-1)
        if self._mem_stats_available is None:
            self._mem_stats_available = \
                mem_acc.normalize_memory_stats(devices[0]) is not None
            if not self._mem_stats_available:
                return
        in_use = peak = 0
        for d in devices:
            stats = mem_acc.normalize_memory_stats(d)
            if stats is None:
                continue
            in_use += stats.get("bytes_in_use") or 0
            peak = max(peak, stats.get("peak_bytes_in_use") or 0)
        reg = self._telemetry.registry
        reg.gauge("mem_bytes_in_use").set(in_use)
        reg.gauge("mem_peak_bytes_in_use").set(peak)
        if self._tracer is not None:
            self._tracer.instant("hbm_in_use", self._lane_mem,
                                 a0=in_use, a1=peak)

    def _use_loss_scaler(self):
        return self.fp16_enabled()

    @property
    def _offload(self):
        return getattr(self.optimizer, "needs_host_state", False)

    def _ensure_state_offload(self, batch):
        """ZeRO-Offload state: device params/accum, HOST fp32 master +
        optimizer moments (reference stage2.py:349-365 cpu_offload branch)."""
        import jax
        import jax.numpy as jnp

        t0 = time.time()
        dev_batch = self._shard_batch(batch)
        init_rng, state_rng = jax.random.split(self._init_rng)
        params_template = jax.eval_shape(
            lambda r, b: self.module.init(r, b), init_rng, dev_batch)
        self._build_shardings(jax.tree_util.tree_map(
            lambda l: jax.ShapeDtypeStruct(l.shape, jnp.float32),
            params_template))
        param_sh = self._shardings.params

        # init on host, keep fp32 master there, push compute params down
        try:
            host_dev = jax.local_devices(backend="cpu")[0]
        except RuntimeError:  # pragma: no cover
            host_dev = jax.local_devices()[0]
        with jax.default_device(host_dev):
            params_f32 = self.module.init(init_rng, batch)
        # np.array(copy=True): device_get of an already-fp32 CPU array is a
        # zero-copy READ-ONLY view, and the host Adam updates masters in
        # place (bf16/fp16 configs hid this — their dtype cast forced a
        # writable copy; fp32 offload crashed)
        host_master = jax.tree_util.tree_map(
            lambda l: np.array(jax.device_get(l), dtype=np.float32,
                               copy=True),
            params_f32)
        self._host_master_flat, self._host_treedef = \
            jax.tree_util.tree_flatten(host_master)
        self._host_opt = self.optimizer.init_state(host_master)

        with jax.set_mesh(self.mesh):
            params = jax.tree_util.tree_map(
                lambda l, sh: jax.device_put(
                    np.asarray(l, dtype=self.compute_dtype), sh),
                host_master, param_sh)
        # host-side fp32 gradient accumulators (only this process's shard
        # regions are ever written/read) + in-flight async fetches
        self._host_grad_accum = None
        self._pending_fetches = []
        self._offload_regions_cache = None

        # scaler value lives in device state (the micro fn reads loss_scale
        # in jit); the update POLICY runs host-side via the shared
        # DynamicLossScaler — one implementation of hysteresis, not three
        scaler = None
        self._host_scaler = None
        if self._use_loss_scaler():
            from deepspeed_tpu.runtime.fp16.loss_scaler import CreateLossScaler

            args = dict(self._config.dynamic_loss_scale_args or {})
            args.setdefault("init_scale", self._config.initial_dynamic_scale)
            self._host_scaler = CreateLossScaler(
                static_loss_scale=self._config.loss_scale or 0,
                dynamic_scale_args=args)
            scaler = make_loss_scale_state(self._host_scaler.cur_scale)
        self._host_skipped = 0

        # scalars must carry the mesh's replicated sharding (not
        # SingleDeviceSharding): multi-process checkpointing can only
        # serialize globally-addressable arrays
        rep = mesh_lib.replicated(self.mesh)
        put_rep = lambda x: jax.device_put(x, rep)
        if scaler is not None:
            scaler = jax.tree_util.tree_map(put_rep, scaler)
        self.state = TrainState(
            step=put_rep(jnp.int32(0)), micro_step=put_rep(jnp.int32(0)),
            params=params, opt_state=(), master=None, accum=(),
            scaler=scaler, skipped_steps=put_rep(jnp.int32(0)),
            rng=put_rep(state_rng))
        n_params = sum(l.size for l in self._host_master_flat)
        log_dist(
            f"Initialized ZeRO-Offload state: {n_params/1e6:.1f}M params "
            f"(fp32 master + moments on host, "
            f"{'AVX' if getattr(self.optimizer, 'using_native', False) else 'numpy'} "
            f"Adam) in {time.time()-t0:.1f}s", ranks=[0])

    def _ensure_state(self, batch):
        if self.state is not None:
            return
        if self._offload:
            return self._ensure_state_offload(batch)
        import jax
        import jax.numpy as jnp

        t0 = time.time()
        dev_batch = self._shard_batch(batch)
        init_rng, state_rng = jax.random.split(self._init_rng)

        params_template = jax.eval_shape(
            lambda r, b: self.module.init(r, b), init_rng, dev_batch)
        # master template in fp32, compute params in compute dtype
        self._build_shardings(
            jax.tree_util.tree_map(
                lambda l: jax.ShapeDtypeStruct(l.shape, jnp.float32), params_template))

        param_sh = self._shardings.params
        master_sh = self._shardings.master

        def init_fn(rng, b):
            params_f32 = jax.tree_util.tree_map(
                lambda l: l.astype(jnp.float32), self.module.init(rng, b))
            return params_f32

        with jax.set_mesh(self.mesh):
            init_jit = jax.jit(init_fn,
                               out_shardings=master_sh if self.mixed_precision else param_sh)
            params_f32 = init_jit(init_rng, dev_batch)

            if self.mixed_precision:
                cast_jit = jax.jit(
                    lambda p: jax.tree_util.tree_map(
                        lambda l: l.astype(self.compute_dtype), p),
                    out_shardings=param_sh)
                params = cast_jit(params_f32)
                master = params_f32
            else:
                params = params_f32
                master = None

            opt_init_jit = jax.jit(self.optimizer.init_state,
                                   out_shardings=self._shardings.opt_state)
            opt_state = opt_init_jit(master if self.mixed_precision else params)

            accum_template = master if self.mixed_precision else params
            accum_jit = jax.jit(
                lambda p: jax.tree_util.tree_map(
                    lambda l: jnp.zeros(l.shape, jnp.float32), p),
                out_shardings=self._shardings.accum)
            accum = accum_jit(accum_template)

        scaler = None
        if self._use_loss_scaler():
            args = self._config.dynamic_loss_scale_args or {}
            if self._config.loss_scale and self._config.loss_scale > 0:
                scaler = make_loss_scale_state(self._config.loss_scale)
            else:
                scaler = make_loss_scale_state(
                    args.get("init_scale", self._config.initial_dynamic_scale),
                    delayed_shift=args.get("delayed_shift", 1))

        self.state = TrainState(
            step=jnp.int32(0), micro_step=jnp.int32(0), params=params,
            opt_state=opt_state, master=master, accum=accum, scaler=scaler,
            skipped_steps=jnp.int32(0), rng=state_rng)
        n_params = sum(int(x.size) for x in jax.tree_util.tree_leaves(params))
        log_dist(f"Initialized model state: {n_params/1e6:.1f}M params "
                 f"in {time.time()-t0:.1f}s", ranks=[0])

    def _shard_batch(self, batch):
        """Host batch -> device arrays with dim0 sharded over 'data'."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = self.mesh

        dp = self.dp_world_size

        def put(x):
            x = np.asarray(x)
            if x.ndim == 0:
                # scalars (e.g. pld_theta) replicate
                return jax.device_put(x, NamedSharding(mesh, P()))
            if x.shape[0] % max(1, dp // jax.process_count()) != 0:
                raise ValueError(
                    f"Batch dim0={x.shape[0]} is not divisible by the local "
                    f"data-parallel degree; feed "
                    f"train_micro_batch_size_per_gpu*local_dp = "
                    f"{self.train_micro_batch_size_per_gpu() * self.local_dp_size} rows")
            # dim1 (sequence) shards over 'seq' when a seq axis exists:
            # Ulysses-style sequence parallelism (parallel/ulysses.py)
            seq = ["seq"] if self.sp_world_size > 1 and x.ndim >= 2 else []
            if seq and x.shape[1] % self.sp_world_size != 0:
                raise ValueError(
                    f"Batch dim1 (sequence)={x.shape[1]} is not divisible by "
                    f"the 'seq' mesh axis size {self.sp_world_size}; pad the "
                    f"sequence so each seq-parallel rank gets equal tokens")
            sh = NamedSharding(mesh, P(*(["data"] + seq
                                         + [None] * (x.ndim - 1 - len(seq)))))
            if jax.process_count() > 1:
                return jax.make_array_from_process_local_data(sh, x)
            return jax.device_put(x, sh)

        return jax.tree_util.tree_map(put, batch)

    # ------------------------------------------------------------------
    # jitted steps
    # ------------------------------------------------------------------
    def _scaler_hparams(self):
        args = self._config.dynamic_loss_scale_args or {}
        return dict(
            scale_window=args.get("scale_window", 1000),
            min_scale=args.get("min_scale", 1.0),
            delayed_shift=args.get("delayed_shift", 1),
            dynamic=self.dynamic_loss_scale())

    def _make_micro_fn(self):
        import jax
        import jax.numpy as jnp

        gas = self.gradient_accumulation_steps()
        model = self.module

        csr_exchange = self._make_csr_grad_exchange() \
            if getattr(self, "_csr_dp_flags", None) is not None else None
        qgz_exchange = self._make_quantized_grad_exchange() \
            if getattr(self, "_qgz_armed", False) else None
        s3_gather = self._make_stage3_gather() \
            if getattr(self, "_s3_sched_armed", False) else None

        def micro(state: TrainState, batch):
            rng = jax.random.fold_in(state.rng, state.micro_step + state.step * 131071)
            scale = state.scaler.loss_scale if state.scaler is not None \
                else jnp.float32(1.0)

            if csr_exchange is not None:
                grads, loss = csr_exchange(state.params, batch, rng, scale)
            elif qgz_exchange is not None:
                grads, loss = qgz_exchange(state.params, batch, rng, scale)
            else:
                def loss_fn(params):
                    # scheduled stage-3: ONE planned quantized gather per
                    # partitioned leaf; its output is a vjp residual, so
                    # the backward reuses it instead of regathering
                    full = s3_gather(params) if s3_gather is not None \
                        else params
                    loss, metrics = model.loss(full, batch, rng, train=True)
                    return loss.astype(jnp.float32) * scale / gas, (loss, metrics)

                grads, (loss, metrics) = jax.grad(loss_fn, has_aux=True)(state.params)
            accum = jax.tree_util.tree_map(
                lambda a, g: a + g.astype(jnp.float32), state.accum, grads)
            new_state = state._replace(accum=accum, micro_step=state.micro_step + 1)
            return new_state, loss

        return micro

    def _sparse_row_capacity(self, batch):
        """CSR row capacity from batch SHAPES (trace-time ints): the model's
        sparse_grad_tokens, falling back to the total integer-leaf size.
        Zero capacity would silently zero every sparse gradient, so it
        raises instead — shared by the offload D2H stream and the DP wire."""
        import jax
        import jax.numpy as jnp

        model = self.module
        if hasattr(model, "sparse_grad_tokens"):
            tokens = int(model.sparse_grad_tokens(batch))
        else:
            tokens = sum(
                int(np.prod(l.shape))
                for l in jax.tree_util.tree_leaves(batch)
                if jnp.issubdtype(jnp.asarray(l).dtype, jnp.integer))
        if tokens <= 0:
            raise ValueError(
                "sparse_gradients: cannot size the CSR row capacity — the "
                "batch has no integer leaves and the model does not define "
                "sparse_grad_tokens(batch); truncating rows would silently "
                "corrupt gradients")
        return tokens

    def _make_csr_grad_exchange(self):
        """Gradient computation + exchange with 'data' manual: sparse-flagged
        leaves skip the dense psum and all-gather CSR rows instead (row
        capacity = local lookup tokens, from the model's sparse_grad_tokens
        or the batch's integer-leaf sizes); dense leaves pmean as GSPMD
        would. Returns (grads mesh-averaged dense, loss pmean'd) — from the
        accumulator onward nothing downstream changes.

        Reference swaps the allreduce for sparse all-gather in
        deepspeed/runtime/engine.py:1227-1265; the traffic win is proved by
        an HLO byte test (tests/unit/test_csr.py)."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        from deepspeed_tpu.runtime.csr_tensor import CSRTensor

        mesh = self.mesh
        gas = self.gradient_accumulation_steps()
        model = self.module
        flags = self._csr_dp_flags
        dp = self.dp_world_size
        pspec = self._onebit_state_spec().params

        def body(params, batch, rng, scale):
            rng = jax.random.fold_in(rng, jax.lax.axis_index("data"))

            def loss_fn(p):
                loss, _ = model.loss(p, batch, rng, train=True)
                return loss.astype(jnp.float32) * scale / gas, loss

            grads, loss = jax.grad(loss_fn, has_aux=True)(params)
            # static row capacity from LOCAL batch shapes (trace-time ints)
            tokens = self._sparse_row_capacity(batch)

            def exchange(flag, g):
                if not flag:
                    return jax.lax.pmean(g, "data")
                # nonzero rows <= local lookup tokens by construction, so
                # capacity cannot drop gradient rows
                cap = min(tokens, g.shape[0])
                csr = CSRTensor.from_dense(g, max_rows=cap)
                idx = jax.lax.all_gather(csr.indices, "data")   # (dp, cap)
                vals = jax.lax.all_gather(csr.values, "data")
                flat_idx = idx.reshape(-1)
                valid = flat_idx >= 0
                flat_vals = vals.reshape((-1,) + vals.shape[2:])
                flat_vals = jnp.where(
                    valid[:, None] if flat_vals.ndim == 2 else valid,
                    flat_vals, 0)
                dense = jnp.zeros(g.shape, flat_vals.dtype)
                return dense.at[jnp.maximum(flat_idx, 0)].add(flat_vals) / dp

            grads = jax.tree_util.tree_map(exchange, flags, grads)
            return grads, jax.lax.pmean(loss, "data")

        def run(params, batch, rng, scale):
            batch_spec = jax.tree_util.tree_map(
                lambda x: P() if x.ndim == 0 else
                P(*(["data"] + [None] * (x.ndim - 1))), batch)
            return jax.shard_map(
                body, mesh=mesh,
                in_specs=(pspec, batch_spec, P(), P()),
                out_specs=(pspec, P()),
                axis_names={"data"}, check_vma=False)(params, batch, rng,
                                                      scale)

        return run

    def _accum_data_dims(self):
        """Per-leaf dim the ZeRO accumulator spec shards over 'data' (None =
        replicated leaf).  Drives which gradient leaves ride the quantized
        reduce-scatter and where their shard lands."""
        import jax
        from jax.sharding import NamedSharding

        return jax.tree_util.tree_map(
            _spec_data_dim, self._shardings.accum,
            is_leaf=lambda x: isinstance(x, NamedSharding))

    def _make_quantized_grad_exchange(self):
        """Gradient computation + exchange with 'data' manual: the stage-2
        reduce-scatter becomes quantize -> all_to_all -> local reduce ->
        dequantize (the ZeRO++ qgZ shape, custom_collectives.
        quantized_reduce_scatter), optionally hierarchical.  Shardable
        leaves come back as the device's fp32 accumulator shard (out_specs
        put 'data' on the same dim the ZeRO accum spec shards), so the
        downstream accum add is collective-free; leaves too small to shard
        pmean densely as GSPMD would.

        Wire bytes drop ~4x vs the fp32 reduce-scatter (int8 + per-block
        fp32 scales at block 128) — asserted analytically by
        comm_volume_report() and tests/unit/test_quantization.py."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        from deepspeed_tpu.runtime.custom_collectives import \
            quantized_reduce_scatter

        mesh = self.mesh
        gas = self.gradient_accumulation_steps()
        model = self.module
        dp = self.dp_world_size
        block = self._config.zero_config.quantization_block_size
        intra = getattr(self, "_qgz_intra", 0)
        state_spec = self._onebit_state_spec()
        pspec = state_spec.params
        grads_out_spec = state_spec.accum
        dims = self._accum_data_dims()

        def body(params, batch, rng, scale):
            rng = jax.random.fold_in(rng, jax.lax.axis_index("data"))

            def loss_fn(p):
                loss, _ = model.loss(p, batch, rng, train=True)
                return loss.astype(jnp.float32) * scale / gas, loss

            grads, loss = jax.grad(loss_fn, has_aux=True)(params)

            def exchange(dim, g):
                if dim is None:
                    return jax.lax.pmean(g, "data")
                return quantized_reduce_scatter(
                    g, "data", dim=dim, block_size=block, intra_size=intra)

            # is_leaf: a None dim means "replicated leaf", not an empty
            # subtree — without it tree_map drops the entry entirely
            grads = jax.tree_util.tree_map(exchange, dims, grads,
                                           is_leaf=lambda x: x is None)
            return grads, jax.lax.pmean(loss, "data")

        def run(params, batch, rng, scale):
            batch_spec = jax.tree_util.tree_map(
                lambda x: P() if x.ndim == 0 else
                P(*(["data"] + [None] * (x.ndim - 1))), batch)
            return jax.shard_map(
                body, mesh=mesh,
                in_specs=(pspec, batch_spec, P(), P()),
                out_specs=(grads_out_spec, P()),
                axis_names={"data"}, check_vma=False)(params, batch, rng,
                                                      scale)

        return run

    def _make_micro_offload_fn(self):
        """Offload micro step: no device accumulator — gradients are an
        OUTPUT (fp32, ZeRO-sharded via out_shardings), streamed to the host
        which owns accumulation + the Adam step."""
        import jax
        import jax.numpy as jnp

        gas = self.gradient_accumulation_steps()
        model = self.module

        sparse_flags = getattr(self, "_offload_sparse_flags", None)

        def micro(state: TrainState, batch):
            rng = jax.random.fold_in(state.rng,
                                     state.micro_step + state.step * 131071)

            def loss_fn(params):
                loss, metrics = model.loss(params, batch, rng, train=True)
                scale = state.scaler.loss_scale if state.scaler is not None \
                    else 1.0
                return loss.astype(jnp.float32) * scale / gas, loss

            grads, loss = jax.grad(loss_fn, has_aux=True)(state.params)
            grads = jax.tree_util.tree_map(
                lambda g: g.astype(jnp.float32), grads)
            if sparse_flags is not None:
                from deepspeed_tpu.runtime.csr_tensor import CSRTensor

                # row capacity (static per trace): an embedding grad has
                # nonzero rows only for looked-up ids, so (indices, values)
                # @ capacity rows beat the dense (vocab, dim) table on the
                # D2H wire. Models declare their lookup-token count via
                # sparse_grad_tokens(batch); the fallback counts every
                # integer leaf, which over-reserves when labels/masks ride
                # along (correct, just a smaller saving).
                tokens = self._sparse_row_capacity(batch)

                def maybe_csr(flag, g):
                    if not flag:
                        return g
                    cap = min(tokens, g.shape[0])
                    csr = CSRTensor.from_dense(g, max_rows=cap)
                    # capacity under-report (e.g. a wrong
                    # sparse_grad_tokens) would silently DROP gradient
                    # rows; the overflow count travels with the leaf and
                    # the host consume raises on it
                    nnz = jnp.sum(jnp.any(g != 0, axis=tuple(
                        range(1, g.ndim))).astype(jnp.int32))
                    return {"csr_indices": csr.indices,
                            "csr_values": csr.values,
                            "csr_dropped": jnp.maximum(nnz - cap, 0)}

                grads = jax.tree_util.tree_map(maybe_csr, sparse_flags,
                                               grads)
            new_state = state._replace(micro_step=state.micro_step + 1)
            return new_state, loss, grads

        return micro

    # ------------------------------------------------------------------
    # offload host-side gradient streaming
    # ------------------------------------------------------------------
    def _offload_regions(self):
        """Unique addressable (leaf_index, numpy_index, owned) regions of
        the ZeRO grad sharding — the slices of each full-shape array this
        process holds. `owned` is True on exactly ONE process per distinct
        region (the lowest process index holding it): cross-process
        reductions like the gradient norm must count a region once even
        when a leaf stays replicated over 'data' (zero_merge_spec leaves
        non-divisible leaves replicated). Cached; layouts are static."""
        if self._offload_regions_cache is not None:
            return self._offload_regions_cache
        import jax

        my_proc = jax.process_index()
        regions = []
        sh_flat = jax.tree_util.tree_leaves(self._offload_region_sh)
        for i, (master, sh) in enumerate(zip(self._host_master_flat,
                                             sh_flat)):
            imap = sh.devices_indices_map(tuple(master.shape))
            owner = {}
            for d, idx in imap.items():
                key = tuple((s.start, s.stop, s.step) for s in idx)
                owner[key] = min(owner.get(key, d.process_index),
                                 d.process_index)
            seen = set()
            for d in sh.addressable_devices:
                idx = imap[d]
                key = tuple((s.start, s.stop, s.step) for s in idx)
                if key in seen:
                    continue
                seen.add(key)
                regions.append((i, idx, owner[key] == my_proc))
        self._offload_regions_cache = regions
        return regions

    @staticmethod
    def _is_csr_leaf(x):
        return isinstance(x, dict) and "csr_indices" in x

    def _start_grad_fetch(self, grads):
        """Kick off async D2H copies of this process's grad shards; returns
        the per-master-leaf list (dense arrays or CSR {indices, values}
        pairs) for later consumption. The copy overlaps the next
        micro-batch's device compute (reference stage2.py:876-958 overlaps
        D2H on a side stream the same way)."""
        import jax

        flat = jax.tree_util.tree_flatten(grads, is_leaf=self._is_csr_leaf)[0]
        for leaf in flat:
            arrs = (list(leaf.values()) if self._is_csr_leaf(leaf)
                    else [leaf])
            for a in arrs:
                for s in a.addressable_shards:
                    s.data.copy_to_host_async()
        return flat

    def _consume_grad_fetch(self, flat):
        """Accumulate a fetched micro-batch's local grad shards into the
        host fp32 buffers (allocated lazily, full-shape; only this
        process's regions are ever touched). CSR leaves scatter-add their
        valid rows into the full-shape buffer."""
        if self._host_grad_accum is None:
            self._host_grad_accum = [np.zeros(m.shape, np.float32)
                                     for m in self._host_master_flat]
        for buf, leaf in zip(self._host_grad_accum, flat):
            if self._is_csr_leaf(leaf):
                dropped = int(np.asarray(leaf["csr_dropped"]))
                if dropped:
                    raise RuntimeError(
                        f"sparse_gradients: CSR capacity too small — "
                        f"{dropped} nonzero gradient rows were dropped; "
                        f"fix the model's sparse_grad_tokens(batch) to "
                        f"report the true lookup-token count")
                idx = np.asarray(leaf["csr_indices"])
                vals = np.asarray(leaf["csr_values"], dtype=np.float32)
                valid = idx >= 0
                np.add.at(buf, idx[valid], vals[valid])
                continue
            seen = set()
            for s in leaf.addressable_shards:
                key = tuple((sl.start, sl.stop, sl.step) for sl in s.index)
                if key in seen:
                    continue
                seen.add(key)
                buf[s.index] += np.asarray(s.data, dtype=np.float32)

    def _drain_pending_fetches(self):
        for flat in self._pending_fetches:
            self._consume_grad_fetch(flat)
        self._pending_fetches = []

    def _replicate_host_leaves(self, leaves):
        """Fill non-local regions of full-shape host fp32 arrays from peer
        processes: local regions go up ZeRO-sharded, one on-device gather
        replicates, and the full array comes back down. Checkpoint-save
        path only; leaves cycles through (master, m, v) so the grad-shard
        layout tree is tiled over it."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        sh_flat = jax.tree_util.tree_leaves(self._offload_region_sh)
        rep = NamedSharding(self.mesh, P())
        if not hasattr(self, "_jit_replicate"):
            # one cached identity: jit retraces per shape, not per call
            self._jit_replicate = jax.jit(lambda x: x, out_shardings=rep)
        out = []
        with jax.set_mesh(self.mesh):
            for j, arr in enumerate(leaves):
                gsh = sh_flat[j % len(sh_flat)]
                imap = gsh.devices_indices_map(tuple(arr.shape))
                arrs = [jax.device_put(
                            np.ascontiguousarray(arr[imap[d]]), d)
                        for d in gsh.addressable_devices]
                ga = jax.make_array_from_single_device_arrays(
                    tuple(arr.shape), gsh, arrs)
                full = self._jit_replicate(ga)
                out.append(np.asarray(jax.device_get(full),
                                      dtype=np.float32))
        return out

    def _qwz_leaf_meta(self):
        """Static per-leaf plan for the quantized (qwZ) parameter push.

        A leaf rides the int8 gather when its offload sharding is a pure
        'data' split on one dim (TP-mixed leaves keep the dense path — they
        are exotic under offload and the flat int8 layout assumes shard ==
        data coordinate).  Cached; layouts are static."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from deepspeed_tpu.runtime import quantization as qz

        if getattr(self, "_qwz_meta", None) is not None:
            return self._qwz_meta
        dp = self.dp_world_size
        block = self._config.zero_config.quantization_block_size
        sh_flat = jax.tree_util.tree_leaves(self._offload_region_sh)
        metas = []
        for master, gsh in zip(self._host_master_flat, sh_flat):
            spec_axes = [(a if isinstance(a, tuple) else (a,))
                         for a in gsh.spec if a is not None]
            flat_axes = [x for axes in spec_axes for x in axes]
            if flat_axes != ["data"] or master.ndim == 0:
                metas.append(None)
                continue
            d = [i for i, a in enumerate(gsh.spec) if a is not None][0]
            s_d = master.shape[d]
            if s_d % dp != 0:
                metas.append(None)
                continue
            nloc = master.size // dp
            bs, nb, npad = qz.block_layout(nloc, block)
            metas.append({
                "dim": d, "shard_rows": s_d // dp, "nloc": nloc,
                "bs": bs, "nb": nb, "npad": npad,
                "q_sh": NamedSharding(self.mesh, P("data")),
            })
        self._qwz_meta = metas
        return metas

    def _build_param_gather(self):
        """The jitted shard->replicated parameter materialization for the
        offload step.  Dense leaves are an identity whose out_shardings make
        XLA all-gather the compute-dtype shards; qwZ leaves arrive as flat
        int8 blocks + fp32 scales, are FORCED replicated while still int8
        (the sharding constraint pins the all-gather to the 1-byte payload)
        and dequantize locally afterwards."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = self.mesh
        dp = self.dp_world_size
        compute_dtype = self.compute_dtype
        param_sh_flat = jax.tree_util.tree_leaves(self._shardings.params)
        leaf_shapes = [tuple(m.shape) for m in self._host_master_flat]
        metas = self._qwz_leaf_meta() if self._qwz_armed \
            else [None] * len(leaf_shapes)
        rep = NamedSharding(mesh, P())

        def gather(dense_arrs, q_arrs, s_arrs):
            outs = [None] * len(metas)
            di = qi = 0
            for i, meta in enumerate(metas):
                if meta is None:
                    outs[i] = dense_arrs[di]
                    di += 1
                    continue
                q = jax.lax.with_sharding_constraint(q_arrs[qi], rep)
                s = jax.lax.with_sharding_constraint(s_arrs[qi], rep)
                qi += 1
                rows = (q.reshape(dp, meta["nb"], meta["bs"])
                        .astype(jnp.float32)
                        * s.reshape(dp, meta["nb"])[:, :, None])
                rows = rows.reshape(dp, meta["npad"])[:, :meta["nloc"]]
                shape = leaf_shapes[i]
                d = meta["dim"]
                # pieces were flattened host-side with dim d moved to the
                # front, so shard rows stack contiguously along that dim
                moved = (shape[d],) + shape[:d] + shape[d + 1:]
                full = rows.reshape((shape[d],) + moved[1:])
                outs[i] = jnp.moveaxis(full, 0, d).astype(compute_dtype)
            return outs

        return jax.jit(gather, out_shardings=param_sh_flat)

    def _push_local_params(self):
        """Upload this process's updated master slices and all-gather to the
        replicated/TP param layout on device — H2D traffic is O(params/dp)
        per process, the gather rides ICI.  With zero_optimization.
        quantized_weights (qwZ, ZeRO++ arxiv 2306.10209 §4.1) eligible
        leaves upload and gather as blockwise int8 + fp32 scales instead of
        the compute dtype, shrinking both the H2D copy and the on-wire
        all-gather ~2-4x; dequantization to the compute dtype happens
        replicated, after the gather."""
        import jax

        from deepspeed_tpu.runtime import quantization as qz

        dtype_name = str(jax.numpy.dtype(self.compute_dtype))
        sh_flat = jax.tree_util.tree_leaves(self._offload_region_sh)
        metas = self._qwz_leaf_meta() if self._qwz_armed \
            else [None] * len(self._host_master_flat)
        block = self._config.zero_config.quantization_block_size
        dense_arrs, q_arrs, s_arrs = [], [], []
        for master, gsh, meta in zip(self._host_master_flat, sh_flat,
                                     metas):
            imap = gsh.devices_indices_map(tuple(master.shape))
            if meta is None:
                pieces = {}
                for d in gsh.addressable_devices:
                    idx = imap[d]
                    key = tuple((s.start, s.stop, s.step) for s in idx)
                    if key not in pieces:
                        pieces[key] = self.optimizer.cast_to(
                            [master[idx]], dtype_name)[0]
                arrs = [jax.device_put(pieces[tuple(
                            (s.start, s.stop, s.step) for s in imap[d])], d)
                        for d in gsh.addressable_devices]
                dense_arrs.append(jax.make_array_from_single_device_arrays(
                    tuple(master.shape), gsh, arrs))
                continue
            npad, nb = meta["npad"], meta["nb"]
            rows = meta["shard_rows"]
            d_dim = meta["dim"]
            pieces = {}
            for dev in gsh.addressable_devices:
                coord = imap[dev][d_dim].start // rows
                if coord not in pieces:
                    # flatten with the sharded dim leading so the gathered
                    # rows stack contiguously (the gather jit's layout)
                    pieces[coord] = qz.quantize_blockwise_np(
                        np.moveaxis(master[imap[dev]], d_dim, 0), block)
            q_parts, s_parts = [], []
            for dev in gsh.addressable_devices:
                coord = imap[dev][d_dim].start // rows
                qp, sp = pieces[coord]
                q_parts.append(jax.device_put(qp, dev))
                s_parts.append(jax.device_put(sp, dev))
            q_arrs.append(jax.make_array_from_single_device_arrays(
                (self.dp_world_size * npad,), meta["q_sh"], q_parts))
            s_arrs.append(jax.make_array_from_single_device_arrays(
                (self.dp_world_size * nb,), meta["q_sh"], s_parts))
        if self._jit_param_gather is None:
            self._jit_param_gather = self._build_param_gather()
        with jax.set_mesh(self.mesh):
            new_flat = self._jit_param_gather(dense_arrs, q_arrs, s_arrs)
        new_params = jax.tree_util.tree_unflatten(self._host_treedef,
                                                  new_flat)
        self.state = self.state._replace(params=new_params)

    def _make_apply_fn(self):
        import jax
        import jax.numpy as jnp

        clip = self.gradient_clipping()
        scaler_hp = self._scaler_hparams()
        optimizer = self.optimizer
        mixed = self.mixed_precision
        compute_dtype = self.compute_dtype
        # integrity sentinels (ISSUE 13): a build-time Python flag, so a
        # disarmed engine compiles the EXACT pre-integrity program
        # (bit-identical, zero extra compiles — tier-1 pin); an armed one
        # adds the global grad norm + update/param-norm ratio as extra
        # jit outputs riding the existing metrics dict
        sentinels = self._integrity is not None \
            and self._integrity.sentinels_armed

        def _tree_norm(tree):
            return jnp.sqrt(sum(
                jnp.sum(jnp.square(l.astype(jnp.float32)))
                for l in jax.tree_util.tree_leaves(tree)))

        def apply(state: TrainState, lr):
            scale = state.scaler.loss_scale if state.scaler is not None else jnp.float32(1.0)
            # overflow check on raw accumulated (scaled) grads
            finite = jnp.asarray(True)
            for g in jax.tree_util.tree_leaves(state.accum):
                finite &= jnp.all(jnp.isfinite(g))
            overflow = ~finite

            def do_update(st):
                grads = jax.tree_util.tree_map(lambda g: g / scale, st.accum)
                if clip and clip > 0:
                    gnorm = jnp.sqrt(sum(
                        jnp.sum(jnp.square(g))
                        for g in jax.tree_util.tree_leaves(grads)))
                    factor = jnp.minimum(1.0, clip / (gnorm + 1e-6))
                    grads = jax.tree_util.tree_map(lambda g: g * factor, grads)
                elif sentinels:
                    # the sentinel wants the global norm even unclipped
                    gnorm = _tree_norm(grads)
                else:
                    gnorm = jnp.float32(0.0)
                master = st.master if mixed else st.params
                new_master, new_opt = optimizer.update(
                    grads, st.opt_state, master, lr=lr)
                extras = gnorm
                if sentinels:
                    delta = jax.tree_util.tree_map(
                        lambda n, o: n.astype(jnp.float32)
                        - o.astype(jnp.float32), new_master, master)
                    extras = (gnorm, _tree_norm(delta)
                              / (_tree_norm(master) + 1e-12))
                if mixed:
                    new_params = jax.tree_util.tree_map(
                        lambda l: l.astype(compute_dtype), new_master)
                    return st._replace(params=new_params, master=new_master,
                                       opt_state=new_opt, step=st.step + 1), extras
                return st._replace(params=new_master, opt_state=new_opt,
                                   step=st.step + 1), extras

            def skip_update(st):
                zero = jnp.float32(0.0)
                return st._replace(skipped_steps=st.skipped_steps + 1,
                                   step=st.step + 1), \
                    ((zero, zero) if sentinels else zero)

            new_state, extras = jax.lax.cond(overflow, skip_update, do_update, state)
            gnorm = extras[0] if sentinels else extras
            if state.scaler is not None:
                new_scaler = update_loss_scale(new_state.scaler, overflow, **scaler_hp)
                new_state = new_state._replace(scaler=new_scaler)
            zero_accum = jax.tree_util.tree_map(jnp.zeros_like, new_state.accum)
            new_state = new_state._replace(accum=zero_accum, micro_step=jnp.int32(0))
            metrics = {"overflow": overflow, "grad_norm": gnorm,
                       "loss_scale": scale}
            if sentinels:
                metrics["update_ratio"] = extras[1]
            return new_state, metrics

        return apply

    # ------------------------------------------------------------------
    # 1-bit Adam wire-compressed path (shard_map over 'data')
    # ------------------------------------------------------------------
    def _onebit_wire(self) -> bool:
        """True when the optimizer asked for on-the-wire gradient compression
        (OnebitAdam with axis_name set): the fused step then runs under
        shard_map with 'data' manual, so gradients stay device-local and the
        only gradient-sized traffic after freeze_step is the bit-packed
        collective (reference onebit_adam.py:104-228 compresses before the
        network; the GSPMD path would psum densely first).  ZeroOneAdam
        carries axis_name too but owns its own phase-compiled path —
        see _zeroone_wire below."""
        return (getattr(self.optimizer, "axis_name", None) is not None
                and getattr(self.optimizer, "name", "")
                != ZEROONE_ADAM_OPTIMIZER
                and not self._offload)

    def _onebit_frozen(self) -> bool:
        """Static freeze phase for program selection, keyed on OPTIMIZER
        steps (engine steps minus scale-skipped steps — the reference's
        count, onebit_adam.py freeze_step semantics). The skipped count is
        a device scalar: it is read back once per train_batch during warmup
        only, and the phase latches True so the post-freeze steady state
        never syncs."""
        if getattr(self, "_onebit_frozen_latch", False):
            return True
        # skipped >= 0, so while engine steps alone cannot reach the
        # boundary there is nothing to read — keeps warmup free of
        # host-device syncs until the freeze is actually reachable
        if self.global_steps + 1 <= self.optimizer.freeze_step:
            return False
        # canonical counter (device counter + host-offload skips) — do not
        # re-implement the read inline, the two would drift
        skipped = self.skipped_steps \
            if self.state is not None and self.fp16_enabled() else 0
        frozen = (self.global_steps - skipped + 1) > self.optimizer.freeze_step
        if frozen:
            self._onebit_frozen_latch = True
        return frozen

    def _make_onebit_tail(self, frozen):
        """Shared optimizer tail for the wire path: overflow check ->
        compressed/warmup update -> scaler. Runs inside shard_map with 'data'
        manual. `accum` may be device-local (fused path) or replicated
        (forward/backward/step path) — both are valid 1-bit inputs."""
        import jax
        import jax.numpy as jnp

        optimizer = self.optimizer
        mixed = self.mixed_precision
        compute_dtype = self.compute_dtype
        scaler_hp = self._scaler_hparams()

        def tail(st, accum, lr):
            scale = st.scaler.loss_scale if st.scaler is not None \
                else jnp.float32(1.0)
            bad = jnp.float32(0.0)
            for g in jax.tree_util.tree_leaves(accum):
                bad += jnp.sum((~jnp.isfinite(g)).astype(jnp.float32))
            bad = jax.lax.psum(bad, "data")
            overflow = bad > 0

            def do_update(s2):
                master = s2.master if mixed else s2.params
                new_master, new_opt = optimizer.update(
                    accum, s2.opt_state, master, lr=lr, scale=scale,
                    frozen=frozen)
                if mixed:
                    new_params = jax.tree_util.tree_map(
                        lambda l: l.astype(compute_dtype), new_master)
                    return s2._replace(params=new_params, master=new_master,
                                       opt_state=new_opt, step=s2.step + 1)
                return s2._replace(params=new_master, opt_state=new_opt,
                                   step=s2.step + 1)

            def skip_update(s2):
                return s2._replace(skipped_steps=s2.skipped_steps + 1,
                                   step=s2.step + 1)

            new_state = jax.lax.cond(overflow, skip_update, do_update, st)
            if st.scaler is not None:
                new_scaler = update_loss_scale(new_state.scaler, overflow,
                                               **scaler_hp)
                new_state = new_state._replace(scaler=new_scaler)
            zero_accum = jax.tree_util.tree_map(jnp.zeros_like,
                                                new_state.accum)
            new_state = new_state._replace(accum=zero_accum,
                                           micro_step=jnp.int32(0))
            metrics = {"overflow": overflow,
                       "grad_norm": jnp.float32(0.0),
                       "loss_scale": scale}
            return new_state, metrics

        return tail

    def _onebit_state_spec(self):
        """State specs for the wire shard_map: partial-auto shard_map
        in_specs may ONLY name manual axes ('data'); auto axes (TP 'model',
        'pipe') are dropped — GSPMD keeps their placement implicitly."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        def manual_only(axis):
            if axis is None:
                return None
            axes = axis if isinstance(axis, tuple) else (axis,)
            kept = tuple(a for a in axes if a == "data")
            if not kept:
                return None
            return kept if len(kept) > 1 else kept[0]

        return jax.tree_util.tree_map(
            lambda s: P(*(manual_only(a) for a in s.spec)), self._shardings,
            is_leaf=lambda x: isinstance(x, NamedSharding))

    def _make_onebit_fused(self, frozen):
        """Full train step (gas micro-batches + 1-bit update) with 'data'
        manual: per-device gradients never see a dense collective."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        mesh = self.mesh
        gas = self.gradient_accumulation_steps()
        model = self.module
        tail = self._make_onebit_tail(frozen)
        state_spec = self._onebit_state_spec()

        def fused(state, stacked_batch, lr):
            batch_spec = jax.tree_util.tree_map(
                lambda x: P(*([None, "data"] + [None] * (x.ndim - 2))),
                stacked_batch)

            def body(st, local_batch, lr):
                scale = st.scaler.loss_scale if st.scaler is not None \
                    else jnp.float32(1.0)

                def micro(carry, b):
                    accum, i = carry
                    rng = jax.random.fold_in(
                        st.rng, i + st.step * 131071)
                    rng = jax.random.fold_in(
                        rng, jax.lax.axis_index("data"))

                    def loss_fn(params):
                        loss, _ = model.loss(params, b, rng, train=True)
                        return loss.astype(jnp.float32) * scale / gas, loss

                    grads, loss = jax.grad(loss_fn, has_aux=True)(st.params)
                    accum = jax.tree_util.tree_map(
                        lambda a, g: a + g.astype(jnp.float32), accum, grads)
                    return (accum, i + 1), loss

                (accum, _), losses = jax.lax.scan(
                    micro, (st.accum, st.micro_step), local_batch)
                new_state, metrics = tail(st, accum, lr)
                metrics["loss"] = jax.lax.pmean(losses.mean(), "data")
                return new_state, metrics

            metrics_spec = {"overflow": P(), "grad_norm": P(),
                            "loss_scale": P(), "loss": P()}
            return jax.shard_map(
                body, mesh=mesh,
                in_specs=(state_spec, batch_spec, P()),
                out_specs=(state_spec, metrics_spec),
                axis_names={"data"}, check_vma=False)(state, stacked_batch, lr)

        return fused

    def _make_onebit_apply(self, frozen):
        """Optimizer step for the forward/backward/step path: accum arrived
        mesh-averaged from the GSPMD micro steps (identical per device), so
        the update still runs under shard_map for the bit-packed collective."""
        import jax
        from jax.sharding import PartitionSpec as P

        mesh = self.mesh
        tail = self._make_onebit_tail(frozen)
        state_spec = self._onebit_state_spec()

        def apply_(state, lr):
            metrics_spec = {"overflow": P(), "grad_norm": P(),
                            "loss_scale": P()}
            return jax.shard_map(
                lambda st, lr: tail(st, st.accum, lr), mesh=mesh,
                in_specs=(state_spec, P()),
                out_specs=(state_spec, metrics_spec),
                axis_names={"data"}, check_vma=False)(state, lr)

        return apply_

    def _compile_onebit(self):
        import jax

        sh = self._shardings
        if self.gradient_clipping():
            # global-norm clipping needs the dense mean gradient — exactly
            # the collective the wire path exists to avoid (cross terms make
            # ||mean(g_i)|| incomputable from local norms). Refusing beats
            # silently training differently at dp>1 than at dp=1.
            raise ValueError(
                "gradient_clipping is incompatible with the 1-bit Adam "
                "wire-compression path (post-freeze there is no dense "
                "gradient to clip). Disable clipping, or set optimizer "
                "params comm_backend_name='none' to keep the dense path.")
        self._jit_micro = jax.jit(self._make_micro_fn(), donate_argnums=(0,),
                                  out_shardings=(sh, None))
        self._onebit_fused_fns = {b: self._make_onebit_fused(b)
                                  for b in (False, True)}
        self._onebit_apply_fns = {b: self._make_onebit_apply(b)
                                  for b in (False, True)}
        self._onebit_fused_jits = {}
        self._onebit_apply_jits = {}

    # ------------------------------------------------------------------
    # 0/1 Adam wire path (shard_map over 'data', per-phase programs)
    # ------------------------------------------------------------------
    def _zeroone_wire(self) -> bool:
        """True when ZeroOneAdam asked for the packed 1-bit wire
        (axis_name armed by _arm_zeroone): the train step then compiles
        one program per cadence phase — warmup (dense pmean + Adam),
        local (accumulate only, ZERO cross-device collectives) and sync
        (the quantized_all_reduce packed wire + lr*k update)."""
        return (getattr(self.optimizer, "name", "")
                == ZEROONE_ADAM_OPTIMIZER
                and getattr(self.optimizer, "axis_name", None) is not None
                and not self._offload)

    def _zeroone_phase(self):
        """(phase, k_round) for the NEXT optimizer step — host-side
        program selection, a pure function of the completed-optimizer-
        step count (zeroone_cadence), so an elastic resume re-derives
        the phase from restored counters.  Keyed on OPTIMIZER steps
        (engine steps minus scale-skipped steps) like _onebit_frozen;
        the latch only skips the device-counter read while the freeze
        boundary is provably unreachable."""
        opt = self.optimizer
        if not getattr(self, "_zeroone_frozen_latch", False) and \
                self.global_steps + 1 <= opt.var_freeze_step:
            return "warmup", 1
        skipped = self.skipped_steps \
            if self.state is not None and self.fp16_enabled() else 0
        phase, k = opt.cadence(self.global_steps - skipped)
        if phase != "warmup":
            self._zeroone_frozen_latch = True
        return phase, k

    def _make_zeroone_tail(self, phase, k):
        """Optimizer tail for the 0/1 Adam wire path, one per (phase,
        k_round).  Local rounds skip the overflow psum entirely — the
        contract is ZERO cross-device collectives — so non-finite
        gradients ride the per-device accumulator until the sync round's
        check (which scans the accumulator too) catches them, skips the
        update and drops the poisoned round's accumulation."""
        import jax
        import jax.numpy as jnp

        optimizer = self.optimizer
        mixed = self.mixed_precision
        compute_dtype = self.compute_dtype
        scaler_hp = self._scaler_hparams()

        def tail(st, accum, lr):
            scale = st.scaler.loss_scale if st.scaler is not None \
                else jnp.float32(1.0)

            if phase == "local":
                master = st.master if mixed else st.params
                _, new_opt = optimizer.update(
                    accum, st.opt_state, master, lr=lr, scale=scale,
                    phase="local", k_round=k)
                new_state = st._replace(opt_state=new_opt,
                                        step=st.step + 1)
                zero_accum = jax.tree_util.tree_map(
                    jnp.zeros_like, new_state.accum)
                new_state = new_state._replace(accum=zero_accum,
                                               micro_step=jnp.int32(0))
                metrics = {"overflow": jnp.asarray(False),
                           "grad_norm": jnp.float32(0.0),
                           "loss_scale": scale}
                return new_state, metrics

            bad = jnp.float32(0.0)
            for g in jax.tree_util.tree_leaves(accum):
                bad += jnp.sum((~jnp.isfinite(g)).astype(jnp.float32))
            if phase == "sync":
                # local rounds never checked: anything non-finite they
                # accumulated must trip the scaler here
                for a in jax.tree_util.tree_leaves(
                        st.opt_state.local_accum):
                    bad += jnp.sum((~jnp.isfinite(a)).astype(jnp.float32))
            bad = jax.lax.psum(bad, "data")
            overflow = bad > 0

            def do_update(s2):
                master = s2.master if mixed else s2.params
                new_master, new_opt = optimizer.update(
                    accum, s2.opt_state, master, lr=lr, scale=scale,
                    phase=phase, k_round=k)
                if mixed:
                    new_params = jax.tree_util.tree_map(
                        lambda l: l.astype(compute_dtype), new_master)
                    return s2._replace(params=new_params,
                                       master=new_master,
                                       opt_state=new_opt, step=s2.step + 1)
                return s2._replace(params=new_master, opt_state=new_opt,
                                   step=s2.step + 1)

            def skip_update(s2):
                new = s2._replace(skipped_steps=s2.skipped_steps + 1,
                                  step=s2.step + 1)
                if phase == "sync":
                    # the round's accumulation is poisoned — drop it, or
                    # every later sync re-trips on the same non-finite
                    new_opt = s2.opt_state._replace(
                        local_accum=jax.tree_util.tree_map(
                            jnp.zeros_like, s2.opt_state.local_accum))
                    new = new._replace(opt_state=new_opt)
                return new

            new_state = jax.lax.cond(overflow, skip_update, do_update, st)
            if st.scaler is not None:
                new_scaler = update_loss_scale(new_state.scaler, overflow,
                                               **scaler_hp)
                new_state = new_state._replace(scaler=new_scaler)
            zero_accum = jax.tree_util.tree_map(jnp.zeros_like,
                                                new_state.accum)
            new_state = new_state._replace(accum=zero_accum,
                                           micro_step=jnp.int32(0))
            metrics = {"overflow": overflow,
                       "grad_norm": jnp.float32(0.0),
                       "loss_scale": scale}
            return new_state, metrics

        return tail

    def _make_zeroone_fused(self, phase, k):
        """Full train step (gas micro-batches + 0/1 Adam tail) with
        'data' manual.  Local-round programs contain NO cross-device
        collective at all — the loss metric is the device-local mean
        (the next sync round reports the true global loss); warmup/sync
        pmean it as usual."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        mesh = self.mesh
        gas = self.gradient_accumulation_steps()
        model = self.module
        tail = self._make_zeroone_tail(phase, k)
        state_spec = self._onebit_state_spec()

        def fused(state, stacked_batch, lr):
            batch_spec = jax.tree_util.tree_map(
                lambda x: P(*([None, "data"] + [None] * (x.ndim - 2))),
                stacked_batch)

            def body(st, local_batch, lr):
                scale = st.scaler.loss_scale if st.scaler is not None \
                    else jnp.float32(1.0)

                def micro(carry, b):
                    accum, i = carry
                    rng = jax.random.fold_in(
                        st.rng, i + st.step * 131071)
                    rng = jax.random.fold_in(
                        rng, jax.lax.axis_index("data"))

                    def loss_fn(params):
                        loss, _ = model.loss(params, b, rng, train=True)
                        return loss.astype(jnp.float32) * scale / gas, loss

                    grads, loss = jax.grad(loss_fn, has_aux=True)(st.params)
                    accum = jax.tree_util.tree_map(
                        lambda a, g: a + g.astype(jnp.float32), accum, grads)
                    return (accum, i + 1), loss

                (accum, _), losses = jax.lax.scan(
                    micro, (st.accum, st.micro_step), local_batch)
                new_state, metrics = tail(st, accum, lr)
                loss = losses.mean()
                if phase != "local":
                    loss = jax.lax.pmean(loss, "data")
                metrics["loss"] = loss
                return new_state, metrics

            metrics_spec = {"overflow": P(), "grad_norm": P(),
                            "loss_scale": P(), "loss": P()}
            return jax.shard_map(
                body, mesh=mesh,
                in_specs=(state_spec, batch_spec, P()),
                out_specs=(state_spec, metrics_spec),
                axis_names={"data"}, check_vma=False)(state, stacked_batch,
                                                      lr)

        return fused

    def _make_zeroone_apply(self, phase, k):
        """Optimizer step for the forward/backward/step path: accum
        arrived mesh-averaged from the GSPMD micro steps (identical per
        device), so the update still runs under shard_map for the packed
        collective and the per-device residual state."""
        import jax
        from jax.sharding import PartitionSpec as P

        mesh = self.mesh
        tail = self._make_zeroone_tail(phase, k)
        state_spec = self._onebit_state_spec()

        def apply_(state, lr):
            metrics_spec = {"overflow": P(), "grad_norm": P(),
                            "loss_scale": P()}
            return jax.shard_map(
                lambda st, lr: tail(st, st.accum, lr), mesh=mesh,
                in_specs=(state_spec, P()),
                out_specs=(state_spec, metrics_spec),
                axis_names={"data"}, check_vma=False)(state, lr)

        return apply_

    def _compile_zeroone(self):
        import jax

        sh = self._shardings
        if self.gradient_clipping():
            # same incompatibility as the 1-bit path: global-norm clipping
            # needs the dense mean gradient the wire exists to avoid
            raise ValueError(
                "gradient_clipping is incompatible with the 0/1 Adam "
                "wire-compression path (sync rounds never materialize a "
                "dense gradient to clip). Disable clipping, or set "
                "optimizer params comm_backend_name='none' to keep the "
                "dense path.")
        self._jit_micro = jax.jit(self._make_micro_fn(), donate_argnums=(0,),
                                  out_shardings=(sh, None))
        # per-(phase, k_round) program caches, built lazily — k doubles on
        # the cadence schedule, so only a handful of programs ever compile
        self._zeroone_fused_jits = {}
        self._zeroone_apply_jits = {}

    def _fused_callable(self):
        if getattr(self, "_zeroone_fused_jits", None) is not None:
            import jax

            phase, k = self._zeroone_phase()
            if (phase, k) not in self._zeroone_fused_jits:
                self._zeroone_fused_jits[(phase, k)] = jax.jit(
                    self._make_zeroone_fused(phase, k), donate_argnums=(0,),
                    out_shardings=(self._shardings, None))
            return self._zeroone_fused_jits[(phase, k)]
        if getattr(self, "_onebit_fused_fns", None):
            import jax

            frozen = self._onebit_frozen()
            if frozen not in self._onebit_fused_jits:
                self._onebit_fused_jits[frozen] = jax.jit(
                    self._onebit_fused_fns[frozen], donate_argnums=(0,),
                    out_shardings=(self._shardings, None))
            return self._onebit_fused_jits[frozen]
        return self._jit_fused

    def _apply_callable(self):
        if getattr(self, "_zeroone_apply_jits", None) is not None:
            import jax

            phase, k = self._zeroone_phase()
            if (phase, k) not in self._zeroone_apply_jits:
                self._zeroone_apply_jits[(phase, k)] = jax.jit(
                    self._make_zeroone_apply(phase, k), donate_argnums=(0,),
                    out_shardings=(self._shardings, None))
            return self._zeroone_apply_jits[(phase, k)]
        if getattr(self, "_onebit_apply_fns", None):
            import jax

            frozen = self._onebit_frozen()
            if frozen not in self._onebit_apply_jits:
                self._onebit_apply_jits[frozen] = jax.jit(
                    self._onebit_apply_fns[frozen], donate_argnums=(0,),
                    out_shardings=(self._shardings, None))
            return self._onebit_apply_jits[frozen]
        return self._jit_apply

    # ------------------------------------------------------------------
    # program-registry contracts (telemetry/programs.py): the HLO claims
    # each compiled variant must keep, read by program_lint's autopilot
    # ------------------------------------------------------------------
    def _micro_program_contract(self):
        """Contract of the per-micro jit: pure device work, donated
        state; under qgZ (stages 1/2) the gradient exchange it carries
        rides the s8 wire within the analytic per-micro budget."""
        contract = {"host_transfer_free": True, "donates_argnums": (0,)}
        if getattr(self, "_qgz_armed", False) \
                and self.zero_optimization_stage() != 3:
            contract.update(
                wire_dtype="s8",
                comm_budget_key="grad_exchange_bytes_per_step",
                # resolved lazily at lint time: the analytic report needs
                # built state, and the per-step figure covers gas micros
                comm_budget_bytes=lambda: (
                    self.comm_volume_report()["grad_exchange_bytes_per_step"]
                    / max(1, self.gradient_accumulation_steps())))
        return contract

    def _optimizer_wire_sync_contract(self):
        """The 0/1 Adam sync-round wire contract: packed u8/s8 payloads
        plus fp32 block scales; total payload within the analytic
        sync-round budget × dp/(dp-1) ring slack (HLO counts gathered
        OUTPUT bytes), scalar overflow/loss syncs (<= 8 elements)
        excluded."""
        dp = self.dp_world_size

        def budget():
            ow = self.comm_volume_report(refresh=True)["optimizer_wire"]
            return ow["sync_round_bytes"] * dp / max(1, dp - 1) + 1

        return {
            "wire_dtype": ("u8", "s8"),
            "comm_budget_key": "optimizer_wire.sync_round_bytes",
            "comm_budget_bytes": budget,
            "comm_small_op_cutoff": 8,
        }

    def _fused_program_spec(self):
        """(program_name, contract) of the fused-train-step variant the
        NEXT dispatch runs — 0/1 Adam and 1-bit Adam compile one program
        per (phase, k)/frozen state, each with its own wire contract.
        The rng key / step scalars pass through a lax.cond unaliased
        (out_shardings suppresses their buffer-donor entries too), hence
        the donation floor."""
        base = {"host_transfer_free": True, "donates_argnums": (0,),
                "donation_min_elements": 4}
        if self._zeroone_wire():
            phase, k = self._zeroone_phase()
            contract = dict(base)
            if phase == "local":
                # skipped round: NO cross-device collective at all —
                # zero wire bytes is what makes the k-round amortization
                # in comm_accounting honest
                contract["collective_free"] = True
            elif phase == "sync":
                contract.update(self._optimizer_wire_sync_contract())
            return f"zeroone_fused:{phase}_k{k}", contract
        if getattr(self, "_onebit_fused_fns", None):
            frozen = self._onebit_frozen()
            contract = dict(base)
            if frozen:
                # post-freeze 1-bit wire: bit-packed signs + fp32 scales
                contract["wire_dtype"] = ("u8", "s8")
            return f"onebit_fused:{'frozen' if frozen else 'warmup'}", \
                contract
        return "fused_train_step", base

    def _apply_program_spec(self):
        """(program_name, contract) of the optimizer-apply variant the
        NEXT dispatch runs (micro-accumulation path).  Donation floor as
        in :meth:`_fused_program_spec` — the rng key rides the cond
        unaliased."""
        base = {"donates_argnums": (0,), "donation_min_elements": 4}
        if self._zeroone_wire():
            phase, k = self._zeroone_phase()
            contract = dict(base)
            if phase == "local":
                contract["collective_free"] = True
            elif phase == "sync":
                contract.update(self._optimizer_wire_sync_contract())
            return f"zeroone_apply:{phase}_k{k}", contract
        if getattr(self, "_onebit_apply_fns", None):
            frozen = self._onebit_frozen()
            contract = dict(base)
            if frozen:
                contract["wire_dtype"] = ("u8", "s8")
            return f"onebit_apply:{'frozen' if frozen else 'warmup'}", \
                contract
        return "apply_step", base

    def _compile(self):
        if self._jit_micro is not None:
            return
        import jax

        if self._zeroone_wire():
            self._compile_zeroone()
            return

        if self._onebit_wire():
            self._compile_onebit()
            return

        sh = self._shardings
        if self._offload:
            # apply runs on host (CPU Adam); the jitted micro step returns
            # this micro-batch's gradients reduce-SCATTERED over 'data'
            # (out_shardings = zero spec) so each process fetches only its
            # own shard; accumulation happens host-side, overlapped with the
            # next micro-batch's device compute
            self._jit_micro = jax.jit(
                self._make_micro_offload_fn(), donate_argnums=(0,),
                out_shardings=(sh, None, self._offload_grad_sh))
            self._jit_param_gather = None  # built on first step
            return
        micro = self._make_micro_fn()
        apply_ = self._make_apply_fn()

        # donate_argnums on the micro step: params/opt_state/master pass
        # through unchanged and alias input buffers, and the fp32
        # accumulator updates in place — without donation every micro-batch
        # copies the full TrainState (transient 2x peak HBM).  The staged
        # forward()/backward() contract still holds (backward commits the
        # staged state); the cost is that a forward whose result is
        # DISCARDED (no backward) consumes the engine state — callers that
        # want a grad-free forward must use engine.eval()/eval_loss, which
        # never touch the train state.
        self._jit_micro = jax.jit(micro, donate_argnums=(0,),
                                  out_shardings=(sh, None))
        self._jit_apply = jax.jit(apply_, donate_argnums=(0,), out_shardings=(sh, None))

        # scheduled stage-3 staged API: the micro step splits into a
        # non-donating forward (returns the vjp stash) and a backward
        # that donates state + stash — gathered weights free at wgrad
        self._jit_s3_fwd = None
        self._jit_s3_bwd = None
        if getattr(self, "_s3_sched_armed", False):
            self._jit_s3_fwd = jax.jit(self._make_stage3_fwd())
            # no out_shardings: the output TrainState inherits the input
            # shardings (accum add is shard-local through the gather's
            # cotangent constraint), and jax 0.4.37 drops the HLO
            # buffer_donor table — the stash-donation contract — when
            # out_shardings is given alongside donate_argnums
            self._jit_s3_bwd = jax.jit(self._make_stage3_bwd(),
                                       donate_argnums=(0, 1))

        gas = self.gradient_accumulation_steps()

        def fused(state, stacked_batch, lr):
            def body(st, b):
                st, loss = micro(st, b)
                return st, loss

            state, losses = jax.lax.scan(body, state, stacked_batch)
            state, metrics = apply_(state, lr)
            metrics["loss"] = losses.mean()
            return state, metrics

        self._jit_fused = jax.jit(fused, donate_argnums=(0,), out_shardings=(sh, None))

    # ------------------------------------------------------------------
    # public training API (reference semantics)
    # ------------------------------------------------------------------
    def flops_profiler_enabled(self):
        return self._config.flops_profiler_config.enabled

    def flops_profiler_profile_step(self):
        return self._config.flops_profiler_config.profile_step

    def _maybe_profile(self, dev_batch):
        """Print the flops profile at profile_step (reference
        engine.py:817-847 triggers the profiler the same way)."""
        cfg = self._config.flops_profiler_config
        if not cfg.enabled or getattr(self, "_profiled", False):
            return
        if self.global_steps + 1 < cfg.profile_step:
            return
        self._profiled = True
        from deepspeed_tpu.profiling.flops_profiler import FlopsProfiler

        prof = FlopsProfiler(engine=self)
        prof.profile_params(self.state.params)
        comm_report = self.comm_volume_report()
        prof.profile_comm(comm_report if comm_report["grad_path_modeled"]
                          else None)
        micro = self._make_micro_offload_fn() if self._offload \
            else self._make_micro_fn()
        import jax

        with jax.set_mesh(self.mesh):
            prof.profile_fn(micro, self.state, dev_batch, n_timing_runs=3)
        prof.print_model_profile(profile_step=cfg.profile_step,
                                 module_depth=cfg.module_depth,
                                 top_modules=cfg.top_modules,
                                 detailed=cfg.detailed)

    # ------------------------------------------------------------------
    # analytic comm-volume accounting (runtime/comm_accounting.py)
    # ------------------------------------------------------------------
    def _comm_leaf_specs(self):
        """(LeafSpec list, qwZ-eligibility list) for the current state:
        name, shape and the 'data'-sharded dim of every parameter leaf."""
        import jax

        from deepspeed_tpu.runtime import comm_accounting as ca

        if self._offload:
            sh_tree = self._offload_region_sh
        else:
            sh_tree = self._shardings.accum

        from jax.sharding import NamedSharding

        dims = jax.tree_util.tree_leaves(jax.tree_util.tree_map(
            _spec_data_dim, sh_tree,
            is_leaf=lambda x: isinstance(x, NamedSharding)),
            is_leaf=lambda x: x is None)
        names = _leaf_path_names(self.state.params)
        shapes = [tuple(l.shape)
                  for l in jax.tree_util.tree_leaves(self.state.params)]
        leaves = [ca.LeafSpec(name=n, shape=s, shard_dim=dim)
                  for n, s, dim in zip(names, shapes, dims)]
        qwz_ok = [m is not None for m in self._qwz_leaf_meta()] \
            if (self._offload and getattr(self, "_qwz_armed", False)) \
            else [False] * len(leaves)
        return leaves, qwz_ok

    def comm_volume_report(self, refresh=False):
        """Analytic per-step communication volume of the ACTIVE config:
        the exact bytes each device sends, per collective and per optimizer
        step, computed from shapes/dtypes/mesh alone — deterministic on CPU
        (no device or HLO needed), so quantized-collective byte wins are
        assertable in tier-1 tests.

        Covers the ZeRO gradient exchange (dense reduce-scatter/all-reduce
        or the qgZ quantized all_to_alls, x gradient-accumulation steps)
        and the per-step weight materialization: the stage-1/2
        compute-dtype all-gather, the offload push (int8+scales under
        qwZ), and stage 3 — scheduled (one quantized gather per
        partitioned leaf per micro-step) or implicit (dense compute-dtype
        gathers at every use site, counted TWICE per micro for the
        remat'd-backward refetch; the baseline's
        ``implicit_param_gather_bytes_per_step`` prices the same so the
        scheduled path is judged against an honest yardstick).  The 0/1
        Adam wire IS modeled (``optimizer_wire`` section, byte-exact
        against quantization.sign_pack_layout, sync rounds amortized
        over the local-step round).  Not modeled: the CSR-sparse and
        1-bit (OneBitAdam) wire paths (proved by HLO byte tests in
        tests/unit/test_csr.py / test_onebit.py).

        Requires built state — call forward/train_batch/init_from_batch
        first."""
        assert self.state is not None, \
            "call forward/train_batch once (or init_from_batch) before " \
            "comm_volume_report"
        # the 0/1 Adam wire is phase-dependent (dense warmup -> packed
        # sync rounds amortized over k): a cached report from another
        # (phase, k) would misprice the wire, so it invalidates itself
        zeroone_key = self._zeroone_phase() if self._zeroone_wire() else None
        if not refresh and getattr(self, "_comm_report", None) is not None \
                and getattr(self, "_comm_report_zeroone", None) == zeroone_key:
            return self._comm_report
        from deepspeed_tpu.runtime import comm_accounting as ca

        zc = self._config.zero_config
        dp = self.dp_world_size
        stage = self.zero_optimization_stage()
        compute = np.dtype(self.compute_dtype).name
        leaves, qwz_ok = self._comm_leaf_specs()
        qwz_armed = getattr(self, "_qwz_armed", False)

        gas = self.gradient_accumulation_steps()
        s3_sched = getattr(self, "_s3_sched_armed", False)
        if stage == 3 and dp > 1:
            # scheduled: one quantized gather per micro; implicit: XLA
            # gathers per use site — fwd plus the remat'd-bwd refetch
            gathers_per_step = gas if s3_sched else 2 * gas
        else:
            gathers_per_step = 1
        report = ca.volume_report(
            leaves, dp,
            gas=gas,
            quantized_gradients=getattr(self, "_qgz_armed", False),
            quantized_weights=qwz_armed or s3_sched,
            quantized_weights_mask=qwz_ok if qwz_armed else None,
            block_size=zc.quantization_block_size,
            intra_size=getattr(self, "_qgz_intra", 0),
            param_dtype=compute,
            gather_params=dp > 1 and (self._offload
                                      or stage in (1, 2, 3)),
            param_gathers_per_step=gathers_per_step,
            implicit_param_gathers_per_step=(
                2 * gas if stage == 3 and dp > 1 else None))
        report["config"].update({"zero_stage": stage,
                                 "compute_dtype": compute})
        # the accounting models the dense/quantized ZeRO exchange; when the
        # active gradient path is actually CSR-sparse or the 1-bit wire the
        # dense numbers would overstate traffic 10-100x, so the report says
        # so and the per-step metric is withheld (those paths' wins are
        # proved by HLO byte tests instead)
        report["grad_path_modeled"] = not (
            getattr(self, "_csr_dp_flags", None) is not None
            or getattr(self, "_offload_sparse_flags", None) is not None
            or self._onebit_wire())
        if zeroone_key is not None:
            # the 0/1 Adam wire IS modeled (byte-exact against
            # sign_pack_layout): replace the dense grad-exchange pricing
            # with the phase-honest wire figure — dense pmean during
            # warmup, packed sync bytes amortized over the round after
            phase, k_round = zeroone_key
            opt = self.optimizer
            ow = ca.zeroone_volume_report(
                leaves, dp, bits=opt.bits,
                block_size=(opt.quantization_block_size
                            or ca.DEFAULT_BLOCK_SIZE),
                intra_size=opt.intra_size, local_steps_k=k_round, gas=gas)
            ow["phase"] = phase
            report["optimizer_wire"] = ow
            report["grad_path_modeled"] = True
            grad_bytes = ow["warmup_grad_exchange_bytes_per_step"] \
                if phase == "warmup" \
                else ow["amortized_grad_exchange_bytes_per_step"]
            report["grad_exchange_bytes_per_step"] = grad_bytes
            report["total_bytes_per_step"] = \
                grad_bytes + report["param_gather_bytes_per_step"]
            base = report["baseline"]["fp32_grad_exchange_bytes_per_step"]
            report["grad_reduction_vs_fp32"] = \
                base / grad_bytes if grad_bytes else None
        self._comm_report = report
        self._comm_report_zeroone = zeroone_key
        return report

    def _comm_bytes_per_step(self):
        """Cached total for the per-step metrics dict; None when the active
        gradient path is one the accounting does not model (CSR, 1-bit) —
        consumers must not see a dense number for a compressed wire."""
        if self.state is None:
            return None
        report = self.comm_volume_report()
        return report["total_bytes_per_step"] \
            if report["grad_path_modeled"] else None

    def _annotate_comm(self, metrics):
        """Copy a step's metrics dict and attach comm_bytes_per_step (plus
        the dense-vs-quantized parameter-gather split) when the accounting
        models the active wire path."""
        metrics = dict(metrics)
        comm = self._comm_bytes_per_step()
        if comm is not None:
            metrics["comm_bytes_per_step"] = comm
            report = self.comm_volume_report()
            metrics["param_gather_bytes_per_step"] = \
                report["param_gather_bytes_per_step"]
            metrics["param_gather_dense_bytes_per_step"] = \
                report["param_gather_dense_bytes_per_step"]
            metrics["param_gather_quantized_bytes_per_step"] = \
                report["param_gather_quantized_bytes_per_step"]
            ow = report.get("optimizer_wire")
            if ow is not None:
                # the 0/1 Adam wire, amortized over its round; 'phase' is
                # the phase the NEXT step will run (the report prices the
                # steady state around this step, not one micro-history)
                metrics["optimizer_wire_bytes_per_step"] = \
                    metrics["comm_bytes_per_step"] \
                    - report["param_gather_bytes_per_step"]
                metrics["optimizer_wire_sync_round_bytes"] = \
                    ow["sync_round_bytes"]
                metrics["optimizer_wire_k_round"] = \
                    ow["config"]["local_steps_k"]
                metrics["optimizer_wire_phase"] = ow["phase"]
        return metrics

    def train(self, mode=True):
        """torch-parity module mode (reference engine is an nn.Module):
        in eval mode forward() computes the loss WITHOUT gradients —
        inference pays forward cost only, not backward+accum."""
        self._train_mode = bool(mode)
        return self

    def eval(self):
        return self.train(False)

    def forward(self, batch):
        """Compute the micro-batch loss (grads are computed alongside and
        committed by backward(), keeping one-fwd-one-bwd cost parity).
        In eval mode (engine.eval()) this is a grad-free forward.

        The micro step donates the engine state into the staged result, so
        every train-mode forward() MUST be committed by backward() — a
        grad-free/discardable forward is engine.eval() + forward (or
        eval_loss), which never touches the train state."""
        if not self._train_mode:
            return self.eval_loss(batch)
        if self._pending_state is not None \
                or self._pending_s3_stash is not None:
            # fail here with the real story, not deep in XLA with a cryptic
            # "buffer was donated" once the dead state is passed back in
            raise RuntimeError(
                "forward() called twice without backward(): the micro step "
                "donates the engine state into the staged result, so each "
                "train-mode forward must be committed by backward() before "
                "the next one; use engine.eval()/eval_loss for grad-free "
                "forwards")
        if self.state is not None and _tree_has_deleted(self.state,
                                                       first_only=True):
            # a failed donated micro execution invalidated the state with
            # nothing staged (JAX deletes donated inputs at dispatch even
            # when the computation errors) — retrying cannot work; say how
            # to recover instead of surfacing XLA buffer errors
            raise RuntimeError(
                "engine state buffers were donated by a failed micro step; "
                "restore with load_checkpoint(..., auto_resume=True) "
                "before continuing")
        if self.wall_clock_breakdown():
            self.timers(FORWARD_MICRO_TIMER).start()
        if self.progressive_layer_drop is not None:
            # theta rides the batch as a traced scalar (reference injects it
            # as module kwargs, engine.py:823-824)
            batch = dict(batch)
            batch["pld_theta"] = np.float32(
                self.progressive_layer_drop.get_theta())
        self._ensure_state(batch)
        self._compile()
        dev_batch = self._shard_batch(batch)
        self._maybe_profile(dev_batch)
        import jax

        gas = self.gradient_accumulation_steps()
        self._note_mfu_workload(dev_batch, micros_in_batch=gas)
        tr = self._tracer
        _t0 = tr.begin() if tr is not None else 0.0
        with jax.set_mesh(self.mesh):
            if getattr(self, "_jit_s3_fwd", None) is not None:
                # scheduled stage-3: the forward does NOT donate the state
                # — it stays alive; what stages is the vjp stash, whose
                # residuals hold the once-gathered weights for backward
                n_gathered = getattr(
                    getattr(self, "_s3_plan", None), "n_gathered_leaves",
                    None)
                self._register_mfu_jit(
                    "s3_fwd", self._jit_s3_fwd, (self.state, dev_batch),
                    gas, mem_label="stage-3 staged forward: gathered "
                    "weights + vjp residuals (fwd->bwd stash) — the "
                    "footprint stage3_prefetch_budget bounds",
                    contract={
                        # the staged forward gathers each partitioned
                        # leaf EXACTLY once, on the s8 wire (fp32 gathers
                        # are the tiny per-block scales, < 64 elements in
                        # the plan's block geometry)
                        "host_transfer_free": True,
                        "wire_dtype": "s8",
                        "wire_min_elements": 64,
                        "expect_op_counts":
                            [("all-gather", "s8", n_gathered)]
                            if n_gathered else None,
                    })
                loss, self._pending_s3_stash = \
                    self._jit_s3_fwd(self.state, dev_batch)
                self._pending_loss = loss
                if tr is not None:
                    tr.complete("forward_micro", self._lane_train, _t0)
                if self.wall_clock_breakdown():
                    self.timers(FORWARD_MICRO_TIMER).stop()
                return loss
            self._register_mfu_jit(
                "micro_step", self._jit_micro, (self.state, dev_batch),
                gas, mem_label="micro step: donated-in-place train state "
                "+ staged loss + activations",
                contract=self._micro_program_contract())
            if self._offload:
                new_state, loss, grads = self._jit_micro(self.state,
                                                         dev_batch)
                self._pending_grads = grads
            else:
                new_state, loss = self._jit_micro(self.state, dev_batch)
        # torch-parity semantics: gradients land when backward() commits the
        # staged state (the donated input buffers now live inside it).
        self._pending_state = new_state
        self._pending_loss = loss
        if tr is not None:
            tr.complete("forward_micro", self._lane_train, _t0)
        if self.wall_clock_breakdown():
            self.timers(FORWARD_MICRO_TIMER).stop()
        return loss

    def __call__(self, batch):
        return self.forward(batch)

    def backward(self, loss=None, allreduce_gradients=True):
        """Commit the gradients of the last forward (reference engine.py:871).

        In the functional engine the grads were already accumulated by
        forward(); backward() validates call order and handles timing.
        """
        if self.wall_clock_breakdown():
            self.timers(BACKWARD_MICRO_TIMER).start()
        tr = self._tracer
        _t0 = tr.begin() if tr is not None else 0.0
        if self._pending_s3_stash is not None:
            # scheduled stage-3: evaluate the stash (gradients land
            # sharded through the gather's cotangent constraint) and
            # donate it — the gathered weights free here, at wgrad
            import jax

            gas = self.gradient_accumulation_steps()
            self._register_mfu_jit(
                "s3_bwd", self._jit_s3_bwd,
                (self.state, self._pending_s3_stash), gas,
                contract={
                    # the backward reuses the stash residuals: ZERO
                    # all-gathers (one would be a remat refetch), and the
                    # stash (argnum 1) is donated — freed at wgrad, not
                    # held to the end of the batch
                    "host_transfer_free": True,
                    "forbid_collectives": ("all-gather",),
                    "donates_argnums": (1,),
                })
            with jax.set_mesh(self.mesh):
                self.state = self._jit_s3_bwd(self.state,
                                              self._pending_s3_stash)
            self._pending_s3_stash = None
            self.micro_steps += 1
            if tr is not None:
                tr.complete("backward_micro", self._lane_train, _t0)
            if self.wall_clock_breakdown():
                self.timers(BACKWARD_MICRO_TIMER).stop()
            return loss
        assert self._pending_state is not None, \
            "backward() called without a preceding forward()"
        self.state = self._pending_state
        self._pending_state = None
        if self._offload:
            # kick off the async D2H of this micro's local grad shards, then
            # consume the PREVIOUS micro's (its copy overlapped this one's
            # compute). Keeping at most one fetch in flight bounds device
            # memory to one grad tree — gas in-flight trees would cost more
            # HBM than the accumulator this path removed.
            _tg = tr.begin() if tr is not None else 0.0
            fetch = self._start_grad_fetch(self._pending_grads)
            self._pending_grads = None
            self._drain_pending_fetches()
            self._pending_fetches.append(fetch)
            if tr is not None:
                # the host-visible half of the offload gradient exchange
                # (device→host shard stream; the collective half is in-jit)
                tr.complete("grad_exchange_d2h", self._lane_train, _tg)
        self.micro_steps += 1
        if tr is not None:
            tr.complete("backward_micro", self._lane_train, _t0)
        if self.wall_clock_breakdown():
            self.timers(BACKWARD_MICRO_TIMER).stop()
        return loss

    def is_gradient_accumulation_boundary(self):
        return self.micro_steps % self.gradient_accumulation_steps() == 0

    def step(self):
        """Optimizer step at accumulation boundaries (reference engine.py:1016)."""
        if self.wall_clock_breakdown():
            self.timers(STEP_MICRO_TIMER).start()
        assert self._pending_state is None \
            and self._pending_s3_stash is None, \
            "step() called between forward() and backward()"
        if self.is_gradient_accumulation_boundary():
            self._chaos_poison_accum()
            self._take_model_step()
        if self.wall_clock_breakdown():
            self.timers(STEP_MICRO_TIMER).stop()

    def _take_model_step_offload(self):
        """Host-driven step, shard-local: each process updates ONLY the
        master/moment regions backing its own ZeRO grad shards (reference
        stage2.py:876-958,1525-1536), then pushes just those slices back —
        the replicated params materialize via one on-device all-gather over
        ICI instead of a full H2D upload per process."""
        import jax

        tr = self._tracer
        _t0 = tr.begin() if tr is not None else 0.0
        lr = self._advance_lr()
        state = self.state
        self._drain_pending_fetches()
        if self._host_grad_accum is None:  # zero micro-batches ran
            self._host_grad_accum = [np.zeros(m.shape, np.float32)
                                     for m in self._host_master_flat]
        regions = self._offload_regions()
        scale = self._host_scaler.cur_scale \
            if self._host_scaler is not None else 1.0
        finite = all(
            np.isfinite(self._host_grad_accum[i][idx]).all()
            for i, idx, _ in regions)
        clip = self.gradient_clipping()
        # norm counts only owned regions: a leaf replicated over 'data'
        # appears on every process and must not be summed N_proc times
        local_sq = sum(
            float((self._host_grad_accum[i][idx].astype(np.float64) ** 2)
                  .sum()) for i, idx, owned in regions if owned) \
            if (clip or finite) else 0.0
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            stats = multihost_utils.process_allgather(
                np.asarray([local_sq, 0.0 if finite else 1.0]))
            total_sq = float(stats[:, 0].sum())
            finite = float(stats[:, 1].sum()) == 0.0
        else:
            total_sq = local_sq

        if finite:
            gnorm = float(np.sqrt(total_sq)) / scale
            clip_factor = min(1.0, clip / (gnorm + 1e-6)) if clip else 1.0
            masters = [self._host_master_flat[i][idx]
                       for i, idx, _ in regions]
            grads = [self._host_grad_accum[i][idx] for i, idx, _ in regions]
            ms = [self._host_opt["m"][i][idx] for i, idx, _ in regions]
            vs = [self._host_opt["v"][i][idx] for i, idx, _ in regions]
            # region lists are VIEWS into the full host arrays: the kernel
            # updates them in place. The temp state dict's step increment is
            # discarded; the persistent counter advances once below.
            # ds_adam_step divides grads by grad_scale: fold unscale + clip
            self.optimizer.step(
                masters, grads, {"step": self._host_opt["step"],
                                 "m": ms, "v": vs},
                lr=lr, grad_scale=scale / clip_factor)
            self._host_opt["step"] += 1
            self._push_local_params()
            self._last_grad_norm = gnorm
        else:
            self._host_skipped += 1
            self._last_grad_norm = 0.0
        for i, idx, _ in regions:
            self._host_grad_accum[i][idx] = 0.0
        new_scale = scale
        if self._host_scaler is not None:
            self._host_scaler.update_scale(not finite)
            new_scale = self._host_scaler.cur_scale
        if not finite:
            log_dist(f"ZeRO-Offload: OVERFLOW, skipping step "
                     f"{self.global_steps + 1}, scale -> {new_scale:g}",
                     ranks=[0])

        import jax.numpy as jnp

        # fresh scalars take the replicated mesh sharding: host-local
        # SingleDeviceSharding scalars cannot be checkpointed multi-process
        put_rep = lambda x: jax.device_put(x, mesh_lib.replicated(self.mesh))
        scaler = self.state.scaler
        if scaler is not None and new_scale != scale:
            scaler = jax.tree_util.tree_map(
                put_rep, make_loss_scale_state(new_scale))
        self.state = self.state._replace(
            micro_step=put_rep(jnp.int32(0)),
            step=self.state.step + 1, scaler=scaler)
        self.global_steps += 1
        if self.progressive_layer_drop is not None:
            self.progressive_layer_drop.update_state(self.global_steps)
        if tr is not None:
            tr.complete("optimizer_step", self._lane_train, _t0,
                        a0=self.global_steps)
            if not finite:
                tr.instant("overflow_skip", self._lane_train,
                           a0=self.global_steps)
        self._last_metrics = self._annotate_comm(
            {"overflow": not finite,
             "grad_norm": getattr(self, "_last_grad_norm", 0.0),
             "loss_scale": scale})
        mon = self._integrity
        if mon is not None and mon.sentinels_armed:
            # sentinels ride the offload step's HOST values: the grad
            # norm was just computed on host for clipping, overflow is
            # the host finite check — the loss is the one scalar fetch,
            # on a path that already streams every gradient through
            # host memory (update_ratio stays None: the host kernel
            # updates masters in place, a before/after norm would add
            # a full extra pass over the master shards)
            observe_loss = None if self._pending_loss is None else \
                float(jax.device_get(self._pending_loss))
            mon.observe_step(self.global_steps, loss=observe_loss,
                             grad_norm=self._last_grad_norm if finite
                             else None,
                             update_ratio=None, overflow=not finite)
        self._observe_step_outcome(loss=self._pending_loss,
                                   overflow=not finite)
        if self.global_steps % self.steps_per_print() == 0:
            self._report_progress(self.global_steps)

    def _take_model_step(self):
        if self._offload:
            return self._take_model_step_offload()
        lr = self._advance_lr()
        import jax
        import jax.numpy as jnp

        tr = self._tracer
        _t0 = tr.begin() if tr is not None else 0.0
        with jax.set_mesh(self.mesh):
            apply_fn = self._apply_callable()
            apply_name, apply_contract = self._apply_program_spec()
            self._register_mfu_jit("apply_step", apply_fn,
                                   (self.state, jnp.float32(lr)),
                                   program_name=apply_name,
                                   contract=apply_contract)
            new_state, metrics = apply_fn(self.state, jnp.float32(lr))
        self.state = new_state
        self.global_steps += 1
        if tr is not None:
            tr.complete("optimizer_step", self._lane_train, _t0,
                        a0=self.global_steps)
        if self.progressive_layer_drop is not None:
            self.progressive_layer_drop.update_state(self.global_steps)
        self._last_metrics = metrics = self._annotate_comm(metrics)
        self._last_grad_norm = metrics["grad_norm"]
        overflow = None
        observe_loss = self._pending_loss
        mon = self._integrity
        if mon is not None:
            # integrity sentinels ride the step's ONE batched fetch; the
            # watchdog downstream gets the HOST loss value, never a
            # second device transfer of what this fetch already paid for
            fetched = jax.device_get((metrics["overflow"],
                                      self._pending_loss,
                                      metrics["grad_norm"],
                                      metrics["update_ratio"]))
            overflow = bool(fetched[0])
            observe_loss = None if fetched[1] is None else float(fetched[1])
            mon.observe_step(
                self.global_steps, loss=observe_loss,
                grad_norm=float(fetched[2]),
                update_ratio=float(fetched[3]), overflow=overflow)
        if self.fp16_enabled():
            # overflow must be visible when it happens (reference
            # fused_optimizer.py logs every skipped step); one small scalar
            # fetch on the already-host-driven non-fused path
            if overflow is None:
                overflow = bool(jax.device_get(metrics["overflow"]))
            if overflow:
                if tr is not None:
                    # loss-scale event: the scaler halves on this skip
                    tr.instant("overflow_skip", self._lane_train,
                               a0=self.global_steps)
                log_dist(
                    f"OVERFLOW! Skipping step {self.global_steps}; "
                    f"reducing loss scale to "
                    f"{float(jax.device_get(new_state.scaler.loss_scale)):g}",
                    ranks=[0])
        elif self._watchdog is not None and overflow is None:
            overflow = bool(jax.device_get(metrics["overflow"]))
        self._observe_step_outcome(loss=observe_loss,
                                   overflow=overflow)
        if self.global_steps % self.steps_per_print() == 0:
            self._report_progress(self.global_steps)
            self._write_monitor({"lr": lr,
                                 "loss_scale": float(metrics["loss_scale"]),
                                 "grad_norm": float(metrics["grad_norm"])})

    def _advance_lr(self):
        if self.lr_scheduler is not None:
            return float(self.lr_scheduler.step())
        return self._current_lr()

    def train_batch(self, data_iter=None, batch=None):
        """Fused full-batch step: gas micro-batches + optimizer step in ONE jit
        (lax.scan over microbatches).  The fast path used for benchmarks."""
        gas = self.gradient_accumulation_steps()
        if batch is None:
            assert data_iter is not None
            micros = [next(data_iter) for _ in range(gas)]
            batch = _stack_batches(micros)
        if self.progressive_layer_drop is not None:
            batch = dict(batch)
            batch["pld_theta"] = np.full(
                (gas,), self.progressive_layer_drop.get_theta(), np.float32)
        self._ensure_state(_first_micro(batch))
        self._compile()
        import jax
        import jax.numpy as jnp

        from deepspeed_tpu.runtime.resilience import chaos as _chaos

        if _chaos.active() is not None:
            # silent-corruption chaos (ISSUE 13): an armed spike_loss
            # plan scales THIS batch host-side — finite anomalous data
            batch = _chaos.maybe_spike_batch(batch, self.global_steps + 1)
        if self._integrity is not None:
            # cache a host reference to the step's first micro for the
            # duplicate-compute sentinel (O(1), no copy, no device work)
            self._integrity.note_micro(_first_micro(batch))
        if self._offload:
            # apply runs on host: micro-loop on device; each micro's grad
            # shards D2H-copy asynchronously while the NEXT micro computes
            # (host-side accumulation of micro i overlaps device compute of
            # micro i+1 — the reference's migration-stream overlap,
            # stage2.py:876-958)
            self._maybe_profile(self._shard_batch(_first_micro(batch)))
            self.tput_timer.start()
            tr = self._tracer
            _t0 = tr.begin() if tr is not None else 0.0
            losses = []
            prev_fetch = None
            with jax.set_mesh(self.mesh):
                for i in range(gas):
                    dev_micro = self._shard_batch(_micro_at(batch, i))
                    self._note_mfu_workload(dev_micro, micros_in_batch=gas)
                    self._register_mfu_jit(
                        "micro_offload", self._jit_micro,
                        (self.state, dev_micro), gas,
                        contract={"host_transfer_free": True,
                                  "donates_argnums": (0,)})
                    self.state, loss, grads = self._jit_micro(self.state,
                                                              dev_micro)
                    fetch = self._start_grad_fetch(grads)
                    losses.append(loss)
                    if prev_fetch is not None:
                        self._consume_grad_fetch(prev_fetch)
                    prev_fetch = fetch
            if prev_fetch is not None:
                self._consume_grad_fetch(prev_fetch)
            self.micro_steps += gas
            self._pending_loss = jnp.mean(jnp.stack(losses))
            if tr is not None:
                tr.complete("train_batch_micros", self._lane_train, _t0,
                            a0=gas)
            self._chaos_poison_accum()
            self._take_model_step_offload()  # reports progress itself
            self.tput_timer.stop()
            # mean over micro-batches, matching the fused path's metric
            return self._pending_loss
        dev = self._shard_stacked_batch(batch)
        self._maybe_profile(self._shard_batch(_first_micro(batch)))
        lr = self._advance_lr()

        self._chaos_poison_accum()
        self.tput_timer.start()
        self._note_mfu_workload(dev)
        tr = self._tracer
        _t0 = tr.begin() if tr is not None else 0.0
        with jax.set_mesh(self.mesh):
            fused_fn = self._fused_callable()
            fused_name, fused_contract = self._fused_program_spec()
            self._register_mfu_jit(
                "fused_train_step", fused_fn,
                (self.state, dev, jnp.float32(lr)),
                mem_label="fused train step: donated-in-place state + "
                "step metrics + per-micro activations",
                program_name=fused_name, contract=fused_contract)
            new_state, metrics = fused_fn(self.state, dev, jnp.float32(lr))
        self.state = new_state
        self.global_steps += 1
        if tr is not None:
            # the fused jit carries micro fwd/bwd, the grad exchange AND
            # the optimizer step in one dispatch — one span per step
            tr.complete("fused_train_step", self._lane_train, _t0,
                        a0=self.global_steps)
        if self.progressive_layer_drop is not None:
            self.progressive_layer_drop.update_state(self.global_steps)
        self.micro_steps += gas
        self._last_metrics = metrics = self._annotate_comm(metrics)
        self._last_grad_norm = metrics["grad_norm"]
        self.tput_timer.stop()
        # the fused path never syncs host-side; the per-step scalars are
        # only fetched when a watchdog or the integrity monitor is armed
        # — and then as ONE batched device_get (the integrity sentinels
        # RIDE the existing fetch; no second host sync per step)
        overflow = None
        observe_loss = None
        mon = self._integrity
        if mon is not None:
            fetched = jax.device_get((metrics["overflow"], metrics["loss"],
                                      metrics["grad_norm"],
                                      metrics["update_ratio"]))
            overflow = bool(fetched[0])
            # the watchdog's NaN check downstream gets the HOST value —
            # handing it the device array would force a SECOND per-step
            # transfer of the loss this fetch just paid for
            observe_loss = float(fetched[1])
            mon.observe_step(self.global_steps, loss=observe_loss,
                             grad_norm=float(fetched[2]),
                             update_ratio=float(fetched[3]),
                             overflow=overflow)
        elif self._watchdog is not None:
            overflow = bool(jax.device_get(metrics["overflow"]))
            observe_loss = metrics["loss"]
        self._observe_step_outcome(loss=observe_loss, overflow=overflow)
        if self.global_steps % self.steps_per_print() == 0:
            self._report_progress(self.global_steps)
        return metrics["loss"]

    def eval_loss(self, batch):
        import jax

        self._ensure_state(batch)
        if self._jit_eval is None:
            model = self.module

            def ev(state, b):
                loss, metrics = model.loss(state.params, b, state.rng, train=False)
                return loss

            self._jit_eval = jax.jit(ev)
        with jax.set_mesh(self.mesh):
            # _live_state: a validation loss mid-accumulation must read the
            # staged (alive) state, not the donated committed one
            dev_b = self._shard_batch(batch)
            self._register_program("eval_loss", self._jit_eval,
                                   (self._live_state, dev_b),
                                   contract={"host_transfer_free": True})
            loss = self._jit_eval(self._live_state, dev_b)
        if self._watchdog is not None:
            # a long validation loop between optimizer steps is progress,
            # not a stalled step
            self._watchdog.heartbeat()
        return loss

    def _shard_stacked_batch(self, batch):
        """Batch with leading (gas, batch...) dims: shard dim1 over data."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = self.mesh

        def put(x):
            x = np.asarray(x)
            seq = ["seq"] if self.sp_world_size > 1 and x.ndim >= 3 else []
            sh = NamedSharding(mesh, P(*([None, "data"] + seq
                                         + [None] * (x.ndim - 2 - len(seq)))))
            if jax.process_count() > 1:
                return jax.make_array_from_process_local_data(sh, x)
            return jax.device_put(x, sh)

        return jax.tree_util.tree_map(put, batch)

    @property
    def watchdog(self):
        """The TrainingWatchdog (None unless resilience.watchdog.enabled);
        register callbacks via engine.watchdog.add_callback(cb)."""
        return self._watchdog

    def consecutive_skipped_steps(self):
        """Current run of overflow-skipped optimizer steps (resets to 0 on
        any successful step).  Tracked on every host-synced step path, and
        on the fused device path whenever the watchdog is enabled."""
        return self._consecutive_skips

    def _observe_step_outcome(self, loss=None, overflow=None):
        """Shared post-step resilience bookkeeping for every step path:
        maintains the consecutive-skip streak, mirrors recovery progress
        (scale + streak) into _last_metrics, and feeds the watchdog.  On an
        abort verdict an emergency checkpoint is written before the
        WatchdogAlarm propagates."""
        # async checkpoint commit: publish (rename + latest) at the first
        # step boundary after the background seal lands — the commit
        # becomes visible without waiting for the next save/wait call
        if self._pending_commit is not None:
            if self._supervisor is not None:
                # supervised runs hold the commit-failure contract: a
                # failed seal/publish (disk full, kill mid-commit) must
                # not become a step crash the ladder answers with a
                # rollback — the atomic layout guarantees no torn tag
                # became visible, so training continues and the failure
                # is counted (the previous PUBLISHED tag stays the
                # rollback target)
                try:
                    self._finalize_pending_commit(wait=False)
                except Exception as e:  # lint: allow-broad-except —
                    # see contract above; unsupervised runs keep the
                    # raise-at-step-boundary behavior
                    self._supervisor.on_commit_failed(e)
            else:
                self._finalize_pending_commit(wait=False)
        from deepspeed_tpu.runtime.resilience import chaos

        if chaos.active() is not None:
            # silent-corruption chaos (ISSUE 13): armed bit flips land on
            # the just-committed state at the step boundary — AFTER this
            # step's sentinel fetch, so detection starts next step (or at
            # this boundary's vote)
            from deepspeed_tpu.runtime.resilience import \
                integrity as integrity_mod

            integrity_mod.apply_chaos_faults(self)
        if self._integrity is not None and self._supervisor is None:
            # unsupervised escalation: without a TrainingSupervisor there
            # is no rollback ladder, so a confirmed corrupt verdict
            # becomes a watchdog event (abort -> emergency checkpoint,
            # stamped integrity-suspect by the open anomaly window)
            verdict = self._integrity.decide(self, self.global_steps)
            if verdict is not None:
                if self._watchdog is not None:
                    from deepspeed_tpu.runtime.resilience.watchdog import \
                        WatchdogAlarm

                    try:
                        self._watchdog.observe_integrity(self.global_steps,
                                                         verdict)
                    except WatchdogAlarm as alarm:
                        self._emergency_checkpoint(alarm.event)
                        raise
                else:
                    logger.warning(
                        f"integrity: corrupt verdict at step "
                        f"{self.global_steps} with no supervisor and no "
                        f"watchdog armed — nothing will recover this run; "
                        f"verdict: {verdict}")
        if overflow is not None:
            self._consecutive_skips = \
                self._consecutive_skips + 1 if overflow else 0
            # published only when actually observed: the fused train_batch
            # path skips the overflow fetch without a watchdog (stays
            # host-async), and a frozen 0 would read as "no skips ever"
            if isinstance(self._last_metrics, dict):
                metrics = dict(self._last_metrics)
                metrics["consecutive_skips"] = self._consecutive_skips
                self._last_metrics = metrics
        if self._ckpt_metrics is not None and \
                isinstance(self._last_metrics, dict) \
                and "ckpt_commit_ms_foreground" not in self._last_metrics:
            metrics = dict(self._last_metrics)
            metrics.update(self._ckpt_metrics)
            metrics["ckpt_commit_pending"] = \
                int(self._pending_commit is not None)
            self._last_metrics = metrics
        if self._supervisor is not None:
            # supervised-step hook point: restart-count/backoff ladder
            # state rides _last_metrics (and, below, the telemetry step
            # stream) — pure host dict work, nothing on the device path
            self._supervisor.on_engine_step(self)
        if self._telemetry is not None:
            # `mem` lane gauges: HBM in-use/peak watermark per step where
            # the backend reports memory_stats (no-op after one probe on
            # backends that don't — the CPU mesh)
            self._memory_step_gauges()
            # step-aligned telemetry boundary: step_time histogram + one
            # JSONL record of this step's metrics (journal idiom — flush
            # per emit, a crash tears at most the final line)
            self._telemetry.on_step(
                self.global_steps,
                self._last_metrics
                if isinstance(self._last_metrics, dict) else None)
        if self._watchdog is not None:
            from deepspeed_tpu.runtime.resilience.watchdog import \
                WatchdogAlarm

            try:
                self._watchdog.observe_step(self.global_steps, loss=loss,
                                            overflow=bool(overflow))
            except WatchdogAlarm as alarm:
                self._emergency_checkpoint(alarm.event)
                raise
        self._maybe_preempt()

    # ------------------------------------------------------------------
    # numerical integrity (runtime/resilience/integrity.py, ISSUE 13)
    # ------------------------------------------------------------------
    def _arm_integrity(self):
        """Arm the silent-corruption defense when ``resilience.
        integrity.enabled`` asks for it, or warn DISARMED naming every
        blocker.  Armed engines compute the step sentinels (loss, global
        grad norm, update/param-norm ratio) INSIDE the step jits and
        fetch them with the existing one-per-step batched device read —
        no new host syncs; host-stepped paths (ZeRO-Offload, the pipe
        interpreter) feed the loss/grad-norm values they already hold on
        host instead; the cross-replica vote / duplicate-compute
        jits compile lazily on their cadence, never on the step path.
        Disarmed engines hold ``self._integrity = None``: the compiled
        step programs are UNTOUCHED (bit-identical, zero extra compiles
        — tier-1 pin)."""
        self._integrity = None
        res = self._resilience
        if not res.integrity_enabled:
            return
        from deepspeed_tpu.runtime.resilience.integrity import (
            IntegrityConfig, IntegrityMonitor)

        blockers = []
        if self._onebit_wire():
            blockers.append(
                "1-bit Adam wire compression (the shard_map'd update "
                "tail has no per-leaf norm outputs; error-feedback "
                "state is deliberately rank-local, which the vote would "
                "misread as corruption)")
        if blockers:
            log_dist(
                f"numerical-integrity defense DISARMED — "
                f"{'; '.join(blockers)}; silent corruption in this "
                f"configuration is only caught by the NaN/overflow "
                f"watchdog", ranks=[0], level=logging.WARNING)
            return
        cfg = IntegrityConfig.from_resilience(res)
        dp = self.dp_world_size
        vote_armed = True
        vote_gathered = False
        vote_blockers = []
        if dp <= 1:
            vote_blockers.append(
                "dp=1 (a single replica has nobody to disagree with)")
        if not self._integrity_armable:
            vote_blockers.append(
                "PipelineEngine (per-stage params have no cross-stage "
                "'data' replica to vote over; sentinels ride the host "
                "loss/grad-norm the pipe interpreter already fetches)")
        if self._offload:
            vote_blockers.append(
                "cpu_offload=true (the optimizer steps on HOST master "
                "shards and re-pushes device params every step — a "
                "device vote would checksum state the next push "
                "overwrites; sentinels ride the host grad-norm/loss "
                "the streaming path already computes)")
        if vote_blockers:
            vote_armed = False
            log_dist(
                f"integrity cross-replica vote DISARMED — "
                f"{'; '.join(vote_blockers)}; sentinels-only (anomalies "
                f"roll back without a culprit rank)",
                ranks=[0], level=logging.WARNING)
        elif self.zero_optimization_stage() >= 3:
            # stage 3: params are ZeRO-sharded at rest, so the vote
            # all_gather-assembles them inside the cadence jit and each
            # rank folds its OWN assembled copy — asymmetric gather/
            # assembly divergence splits the digest table (the mode a
            # stage-3 forward feeds straight into the matmuls); a shard
            # corrupted at rest assembles identically everywhere and
            # stays the sentinels' case
            vote_gathered = True
        # the dup check replays one micro with REPLICATED params; under
        # stage 3 the param in_specs are 'data'-sharded, so the replayed
        # loss would see shard-shaped weights — gathered mode keeps it off
        dup_armed = vote_armed and not vote_gathered \
            and cfg.dup_check_every_steps > 0
        self._integrity = IntegrityMonitor(
            cfg, dp, sentinels_armed=True, vote_armed=vote_armed,
            dup_armed=dup_armed, vote_gathered=vote_gathered,
            tracer=self._tracer)
        log_dist(
            f"numerical-integrity defense armed: sentinels "
            f"(z>{cfg.z_threshold:g} over a {cfg.window}-step window), "
            f"cross-replica vote="
            f"{('on (gathered)' if vote_gathered else 'on') if vote_armed else 'off'}, "
            f"duplicate-compute check="
            f"{'every %d steps' % cfg.dup_check_every_steps if dup_armed else 'off'}",
            ranks=[0])

    # ------------------------------------------------------------------
    # self-healing supervision (runtime/resilience/supervisor.py, ISSUE 12)
    # ------------------------------------------------------------------
    def _arm_supervisor(self, supervisor):
        """Arm the supervised-step hook points for a TrainingSupervisor,
        or warn DISARMED naming every blocker.  Armed supervision is
        purely host-side observation at step boundaries — the compiled
        device programs are untouched (bit-identical steps, zero extra
        compiles; pinned by tier-1 tests).  Blockers are the things the
        recovery ladder cannot work without: a committed-tag directory
        and the atomic commit discipline (a torn tag is not a rollback
        target).  A missing elasticity config disarms only the
        elastic-restart rung — retry and rollback stay armed — but
        warns, because lost capacity then aborts instead of resharding."""
        self._supervisor = None
        blockers = []
        if not getattr(supervisor, "save_dir", None):
            blockers.append(
                "no save_dir — rollback and elastic restart need a "
                "committed-tag directory")
        if not self._resilience.atomic_checkpoints:
            blockers.append(
                "resilience.atomic_checkpoints is disabled — a torn tag "
                "could become the rollback target")
        if blockers:
            log_dist(
                f"self-healing supervision DISARMED — "
                f"{'; '.join(blockers)}; steps run unsupervised (no "
                f"retry, rollback or elastic restart)",
                ranks=[0], level=logging.WARNING)
            return False
        from deepspeed_tpu.elasticity import elasticity_enabled

        if not elasticity_enabled(self._config._param_dict):
            log_dist(
                "supervisor elastic restart DISARMED — no elasticity "
                "config, so a lost host cannot reshard onto the "
                "survivors (compute_elastic_config has no valid world "
                "set) and lost capacity aborts the run; transient retry "
                "and coordinated rollback stay armed",
                ranks=[0], level=logging.WARNING)
        self._supervisor = supervisor
        log_dist("self-healing supervision armed: heartbeat detection + "
                 "retry/rollback/elastic-restart ladder", ranks=[0])
        return True

    # ------------------------------------------------------------------
    # graceful preemption (topology-elastic restart, ISSUE 7)
    # ------------------------------------------------------------------
    def request_preemption(self):
        """Ask for a graceful shutdown: at the next optimizer-step
        boundary the engine writes a synchronous, atomically committed
        ``preempt_step<N>`` checkpoint (multi-host coordinated via the
        all_agree discipline) and raises
        :class:`~deepspeed_tpu.runtime.resilience.watchdog.GracefulPreemption`.
        Signal-handler safe: only sets a flag."""
        self._preempt_requested = True
        self._preempt_poll_enabled = True

    def install_preemption_handler(self, signals=None):
        """Route SIGTERM (the preemption notice on TPU pods) into
        :meth:`request_preemption`.  Call it on EVERY process of a
        multi-host run — the per-step preemption poll is a collective
        (coordination.any_flag), so a host that never armed it would
        leave peers waiting in the agreement.  Any previously installed
        Python-level handler is CHAINED, not replaced — a process that
        also hosts a serving engine (or any client SIGTERM hook) keeps
        every handler (``signal.signal`` alone is last-wins).  Main
        thread only (a Python signal-handler constraint)."""
        import signal as signal_mod

        from deepspeed_tpu.runtime.resilience.watchdog import \
            chain_signal_handlers

        sigs = chain_signal_handlers(self.request_preemption, signals)
        self._preempt_poll_enabled = True
        log_dist(f"preemption handler installed for "
                 f"{[signal_mod.Signals(s).name for s in sigs]}", ranks=[0])

    def _maybe_preempt(self):
        """Step-boundary preemption poll: OR the local request flag with
        an armed chaos ``preempt_after_steps`` plan, agree across hosts
        (any rank's signal preempts everyone), then save + raise.  The
        collective poll only runs once preemption is armed on this host
        — an idle multi-host run pays nothing."""
        import jax

        from deepspeed_tpu.runtime.resilience import chaos

        want = self._preempt_requested
        if chaos.active() is not None and chaos.consume_preempt_step():
            want = True
        if jax.process_count() > 1:
            if not (self._preempt_poll_enabled or chaos.active() is not None):
                return
            from deepspeed_tpu.runtime.resilience.coordination import \
                any_flag

            want = any_flag(want)
        if not want:
            return
        self._preempt_requested = True  # latch (peer-initiated preempts)
        if self._tracer is not None:
            self._tracer.instant("preempt", self._lane_train,
                                 a0=self.global_steps)
        tag, save_dir = self._preempt_checkpoint()
        from deepspeed_tpu.runtime.resilience.watchdog import \
            GracefulPreemption

        raise GracefulPreemption(
            f"graceful preemption at step {self.global_steps}"
            + (f": committed checkpoint tag {tag!r} under {save_dir}"
               if tag else " (no checkpoint directory known; state NOT "
                          "saved)"),
            tag=tag, save_dir=save_dir)

    def _preempt_checkpoint(self):
        """The forced pre-shutdown save: synchronous (the process is
        about to exit — a background commit thread would die with it),
        atomic, ``latest``-updating (unlike watchdog emergency tags this
        state is HEALTHY, so restarts should resume from it), with the
        exact data position in client_state so the restart neither
        replays nor skips samples.  Returns ``(tag, save_dir)``."""
        from deepspeed_tpu.runtime.resilience import reshard

        # the run's own checkpoint dir FIRST (opposite of the watchdog's
        # emergency preference): the preempt tag holds healthy state and
        # updates `latest`, so it must land where restarts actually look;
        # the emergency dir is only the fallback for never-saved runs
        save_dir = self._last_ckpt_dir \
            or self._resilience.watchdog_emergency_dir
        if not save_dir:
            logger.warning(
                "graceful preemption: no prior save_checkpoint dir and no "
                "resilience.watchdog.emergency_checkpoint_dir configured; "
                "shutting down WITHOUT a checkpoint")
            return None, None
        tag = f"preempt_step{self.global_steps}"
        self.save_checkpoint(
            save_dir, tag=tag,
            client_state={"data_position": reshard.data_position(self)},
            manifest_meta={"preempt": True}, async_commit=False)
        log_dist(f"graceful preemption: committed {tag!r} under "
                 f"{save_dir}", ranks=[0])
        return tag, save_dir

    def _emergency_checkpoint(self, event=None):
        """Final checkpoint before a watchdog abort tears the run down."""
        import jax

        from deepspeed_tpu.runtime.resilience.watchdog import EVENT_STALL

        if self._tracer is not None:
            self._tracer.instant("emergency_checkpoint", self._lane_ckpt,
                                 a0=self.global_steps)

        if event is not None and event.kind == EVENT_STALL \
                and jax.process_count() > 1:
            # stall detection is host-local wall clock: peers may not have
            # fired, and the collective save below would deadlock against
            # their training-step collectives.  Overflow/NaN streaks derive
            # from globally-reduced values, so every host aborts together.
            logger.warning(
                "watchdog abort (stall): skipping emergency checkpoint on a "
                "multi-process run — stall verdicts are host-local and the "
                "collective save would hang peers")
            return
        save_dir = self._resilience.watchdog_emergency_dir \
            or self._last_ckpt_dir
        if not save_dir:
            logger.warning(
                "watchdog abort: skipping emergency checkpoint (no prior "
                "save_checkpoint dir and no resilience.watchdog."
                "emergency_checkpoint_dir configured)")
            return
        try:
            # save_latest=False + the manifest flag: the aborting state may
            # itself be the problem (NaN params on a non-fp16 divergence),
            # so restarts must prefer the last healthy checkpoint — the
            # emergency tag is kept for postmortem and as a last resort.
            # async_commit=False: the process is about to die on the
            # WatchdogAlarm — a background commit thread would die with
            # it, so the final snapshot commits synchronously.
            # data_position in client_state: the postmortem restart must
            # know the exact sample offset, or it replays/skips data
            from deepspeed_tpu.runtime.resilience import reshard

            self.save_checkpoint(save_dir,
                                 tag=f"emergency_step{self.global_steps}",
                                 save_latest=False,
                                 client_state={"data_position":
                                               reshard.data_position(self)},
                                 manifest_meta={"emergency": True},
                                 async_commit=False)
        except Exception as e:
            # best-effort by definition: whatever the save raises, the
            # caller must still see the WatchdogAlarm, not a ckpt error
            logger.error(f"emergency checkpoint failed: "
                         f"{type(e).__name__}: {e}")

    def _chaos_poison_accum(self):
        """Test hook: replace the grad accumulator with NaN when a chaos
        nan_grads plan is armed (no-op in production)."""
        from deepspeed_tpu.runtime.resilience import chaos

        if chaos.active() is None or not chaos.consume_nan_grad_step():
            return
        if self._offload and getattr(self, "_host_grad_accum", None):
            for acc in self._host_grad_accum:
                acc.fill(np.nan)
            return
        import jax
        import jax.numpy as jnp

        poisoned = jax.tree_util.tree_map(
            lambda a: jnp.full_like(a, jnp.nan), self.state.accum)
        self.state = self.state._replace(accum=poisoned)

    def _report_progress(self, step):
        lr = self._current_lr()
        scale = self.loss_scale() if self.fp16_enabled() else 1
        log_dist(f"step={step}, skipped={self.skipped_steps}, lr={lr:g}, "
                 f"scale={scale:g}", ranks=[0])

    def _write_monitor(self, scalars: dict):
        if self.summary_writer is None:
            return
        for tag, v in scalars.items():
            self.summary_writer.add_scalar(f"Train/Samples/{tag}", float(v),
                                           self.global_steps)
        self.summary_writer.flush()

    def _checkpoint_tag_validation(self, tag):
        """Cross-process consistency check on the checkpoint tag
        (reference engine.py:1472-1487: min/max allreduce of the tag hash;
        a rank writing under a different tag corrupts the layout)."""
        mode = getattr(self._config, "checkpoint_tag_validation_mode", "WARN")
        import jax

        if mode == "IGNORE" or jax.process_count() == 1:
            return
        import hashlib

        from jax.experimental import multihost_utils

        digest = int.from_bytes(
            hashlib.sha256(str(tag).encode()).digest()[:4], "big")
        arr = np.asarray([digest], dtype=np.int64)
        gathered = multihost_utils.process_allgather(arr)
        lo, hi = gathered.min(), gathered.max()
        if int(lo) != int(hi):
            msg = (f"checkpoint tag {tag!r} is not consistent across "
                   f"processes (hash min {lo} != max {hi})")
            if mode == "FAIL":
                raise AssertionError(msg)
            logger.warning(msg)

    # ------------------------------------------------------------------
    # checkpointing (reference engine.py:1279-1597; layout kept similar)
    # ------------------------------------------------------------------
    def _resolve_ckpt_backend(self, backend):
        """Concrete payload backend for None/'auto' requests: orbax when
        available (sharded write with NO host gather — npz would
        materialize the full TrainState on process 0; a 10B state OOMs
        the host), npz as the tiny/portable fallback."""
        if backend in (None, "auto"):
            try:
                import orbax.checkpoint  # noqa: F401

                return "orbax"
            except ImportError:  # pragma: no cover - orbax is baked in
                return "npz"
        return backend

    def _ckpt_host_snapshot(self, client_state, backend, copy_host=False):
        """Everything the payload writer needs, resident on HOST memory and
        owned by the snapshot (device_get'd / copied), so writing can
        happen on a background thread while training donates and mutates
        the live state.  Device transfers and host-replication collectives
        all happen HERE (the foreground), never in the writer.
        ``copy_host=True`` (async commits) additionally copies mutable
        host-optimizer buffers; the sync path writes before the next step
        can mutate them, so it skips the copy."""
        import jax

        snap = {"backend": backend, "client_state": client_state,
                "num_leaves": len(jax.tree_util.tree_leaves(self.state)),
                "flat": None, "off_leaves": None, "opt_step": None}
        if backend == "npz" and jax.process_index() == 0:
            host_state = jax.device_get(self.state)
            snap["flat"], _ = jax.tree_util.tree_flatten(host_state)
        if self._offload:
            # shard-local stepping means each process's host arrays are
            # only authoritative on its own regions: reassemble full
            # arrays via a device round-trip before rank 0 writes them
            off_leaves = (self._host_master_flat + self._host_opt["m"]
                          + self._host_opt["v"])
            if jax.process_count() > 1:
                off_leaves = self._replicate_host_leaves(off_leaves)
            if copy_host:
                # the host Adam steps these buffers in place; a background
                # writer must see the snapshot-time values
                off_leaves = [np.array(l, copy=True) for l in off_leaves]
            snap["off_leaves"] = off_leaves
            snap["opt_step"] = self._host_opt["step"]
        from deepspeed_tpu.runtime.resilience import reshard

        snap["meta"] = {
            "global_steps": self.global_steps,
            "micro_steps": self.micro_steps,
            "skipped_steps": self.skipped_steps,
            "dp_world_size": self.dp_world_size,
            "backend": backend,
            "lr_scheduler": self.lr_scheduler.state_dict()
            if self.lr_scheduler is not None else None,
            "client_state": client_state,
            "num_leaves": snap["num_leaves"],
            reshard.TOPOLOGY_KEY: reshard.topology_manifest(self),
            reshard.DATA_POSITION_KEY: reshard.data_position(self),
        }
        return snap

    def _write_snapshot_files(self, path, snap):
        """Write one snapshot's payload files into ``path`` — filesystem
        work only (safe on the async commit thread).  Each file write is
        followed by a chaos hook so fault-injection tests can
        kill/corrupt the write at any point."""
        import jax

        from deepspeed_tpu.runtime.checkpoint_utils import leaves_to_npz_dict
        from deepspeed_tpu.runtime.resilience import chaos

        if snap["flat"] is not None:
            fname = os.path.join(path, "model_states.npz")
            self._ckpt_savez(fname, **leaves_to_npz_dict(snap["flat"]))
            chaos.file_written(fname)
        if jax.process_index() == 0:
            if snap["off_leaves"] is not None:
                fname = os.path.join(path, "offload_states.npz")
                self._ckpt_savez(fname,
                                 **leaves_to_npz_dict(snap["off_leaves"]),
                                 opt_step=snap["opt_step"])
                chaos.file_written(fname)
            fname = os.path.join(path, "metadata.pkl")
            with open(fname, "wb") as f:
                pickle.dump(snap["meta"], f)
            chaos.file_written(fname)

    def _write_checkpoint_files(self, path, client_state, backend):
        """Write every payload file of one checkpoint tag into ``path``
        (the temp dir on the atomic path).  Returns the backend used."""
        from deepspeed_tpu.runtime.resilience import chaos

        backend = self._resolve_ckpt_backend(backend)
        if backend == "orbax":
            import orbax.checkpoint as ocp

            ckptr = ocp.StandardCheckpointer()
            ckptr.save(os.path.join(os.path.abspath(path), "orbax_state"),
                       self.state)
            ckptr.wait_until_finished()
            chaos.file_written(os.path.join(path, "orbax_state"))
        self._write_snapshot_files(
            path, self._ckpt_host_snapshot(client_state, backend))
        return backend

    def _ckpt_snapshot_writer(self, client_state, backend):
        """(backend, write_fn) for an ASYNC commit: every device->host
        transfer and mutable-host copy happens NOW on the training
        thread; ``write_fn(path)`` then only touches the filesystem.
        ``backend`` must already be resolved and npz-family (the orbax
        writer gathers from live device state — the arming gate keeps it
        synchronous)."""
        snap = self._ckpt_host_snapshot(client_state, backend,
                                        copy_host=True)
        return backend, lambda path: self._write_snapshot_files(path, snap)

    def _assert_saveable(self):
        assert self.state is not None, \
            "nothing to save; train state not built"
        assert self._pending_state is None \
            and self._pending_s3_stash is None, \
            "save_checkpoint between forward() and backward(): the micro " \
            "step donated the committed state's buffers (or a stage-3 " \
            "stash is in flight) — commit the in-flight micro-batch with " \
            "backward() first"
        if _tree_has_deleted(self.state):
            raise RuntimeError(
                "cannot checkpoint: the train state's buffers were donated "
                "by a failed micro step; restore a previous checkpoint "
                "(load_checkpoint(..., auto_resume=True)) instead of "
                "saving the dead state")

    def _assert_loadable(self):
        assert self.state is not None, \
            "call forward/train_batch once (or init_from_batch) before " \
            "load_checkpoint"

    def _ckpt_savez(self, fname, **arrays):
        """np.savez for checkpoint payloads.  On the atomic path the bytes
        are sha256'd concurrently with the write so the manifest pass does
        not have to re-read and re-hash the file."""
        if self._resilience.atomic_checkpoints:
            from deepspeed_tpu.runtime.resilience.atomic import savez_hashed

            # commit-path helper: callers are the chaos-hooked snapshot
            # writers targeting the atomic temp dir
            savez_hashed(fname, **arrays)  # graftlint: disable=raw-ckpt-write
        else:
            # the sanctioned legacy (resilience.atomic_checkpoints=false)
            # in-place layout — unprotected by design, documented as such
            np.savez(fname, **arrays)  # graftlint: disable=raw-ckpt-write

    def _checkpoint_manifest_meta(self, tag):
        """World/step metadata recorded in the tag manifest (human- and
        tooling-readable without unpickling the payload).  The "backend"
        key is filled in by save_checkpoint once the payload write has
        resolved it.  "topology" + "data_position" make the tag
        topology-elastic: any mesh can read what layout wrote it and
        where the sample stream stood (resilience/reshard.py)."""
        from deepspeed_tpu.runtime.resilience import reshard

        meta = {
            "tag": str(tag),
            "global_steps": self.global_steps,
            "micro_steps": self.micro_steps,
            "world": {
                "dp": self.dp_world_size,
                "mp": self.mp_world_size,
                "sp": self.sp_world_size,
            },
            reshard.TOPOLOGY_KEY: reshard.topology_manifest(self),
            reshard.DATA_POSITION_KEY: reshard.data_position(self),
        }
        if self._integrity is not None:
            # integrity stamp (ISSUE 13): a tag committed INSIDE an
            # unresolved anomaly window holds bytes that verify but
            # numbers that are suspect — auto-resume and the supervisor's
            # rollback-target selection both fall back past it
            meta["integrity_clean"] = bool(self._integrity.clean())
        return meta

    def save_checkpoint(self, save_dir, tag=None, client_state=None,
                        save_latest=True, backend=None, manifest_meta=None,
                        async_commit=None):
        """backend: None/'auto' (orbax when multi-process — sharded write
        without gathering, the fix for replicate-on-save OOM), 'npz'
        (single-file), or 'orbax' (sharded; supports world-size-elastic
        restore via orbax's sharding-aware load).  manifest_meta: extra
        keys merged into the tag manifest (atomic path only).

        With resilience.atomic_checkpoints (default on) the tag is written
        into a temp dir with a checksum manifest, fsync'd, atomically
        renamed into place, and only then is the ``latest`` pointer
        updated — a crash at any point leaves the previous checkpoint
        intact and loadable.

        async_commit (None = resilience.async_commit): snapshot the state
        to host HERE, then run the payload write + streaming hash + fsync
        on a background commit thread; only the atomic rename +
        latest-pointer update stay on the training thread (they run at
        the next step boundary once the seal lands, or in wait_pending_
        commit()).  Returns with the tag NOT yet visible; durability
        semantics and back-pressure are documented in
        docs/tutorials/fault_tolerance.md."""
        import time as _time

        import jax

        t0 = _time.perf_counter()
        # back-pressure: at most one commit in flight — a still-running
        # previous commit is finalized (waiting on its seal) first
        self._finalize_pending_commit(wait=True)
        self._assert_saveable()
        client_state = client_state or {}
        if tag is None:
            tag = f"global_step{self.global_steps}"
        self._checkpoint_tag_validation(tag)
        res = self._resilience
        self._last_ckpt_dir = save_dir
        want_async = res.async_commit if async_commit is None \
            else bool(async_commit)
        if want_async:
            want_async = self._arm_async_commit(backend)
        if want_async:
            backend_r = self._resolve_ckpt_backend(backend)
            meta = self._checkpoint_manifest_meta(tag)
            meta.update(manifest_meta or {})
            meta["backend"] = backend_r
            from deepspeed_tpu.runtime.resilience.atomic import (
                FollowerCommit, PendingCommit, atomic_tag)

            backend_r, write_fn = self._ckpt_snapshot_writer(client_state,
                                                             backend_r)
            hb = self._ckpt_commit_heartbeat()
            if jax.process_count() > 1 and jax.process_index() != 0:
                # npz-family backends write payload on process 0 only;
                # peers hold a placeholder so every rank runs the same
                # finalize choreography (all_agree phases) in lockstep
                self._pending_commit = FollowerCommit().start()
            else:
                commit = atomic_tag(save_dir, tag, meta=meta,
                                    update_latest=save_latest,
                                    fsync=res.fsync)
                self._pending_commit = PendingCommit(
                    commit, write_fn, heartbeat=hb).start()
            self._pending_commit_info = {
                "save_dir": save_dir, "tag": str(tag),
                "backend": backend_r,
                # the supervisor's published-tag tracking (ISSUE 13
                # async-cadence satellite): only a PUBLISHED tag is a
                # rollback target, and its integrity stamp was fixed at
                # commit time, not publish time
                "global_steps": int(meta.get("global_steps",
                                             self.global_steps)),
                "integrity_clean": bool(meta.get("integrity_clean", True)),
            }
            self._ckpt_foreground_ms = (_time.perf_counter() - t0) * 1000.0
            self._publish_ckpt_metrics()
            if self._tracer is not None:
                self._tracer.complete("ckpt_async_submit", self._lane_ckpt,
                                      t0, a0=self.global_steps)
            log_dist(f"Async checkpoint commit in flight for tag {tag!r} "
                     f"(snapshot took "
                     f"{self._ckpt_foreground_ms:.1f} ms foreground; "
                     f"write+hash+fsync on the commit thread)", ranks=[0])
            return True

        if not res.atomic_checkpoints:
            # legacy in-place layout (crash window: torn tag, stale latest)
            path = os.path.join(save_dir, str(tag))
            os.makedirs(path, exist_ok=True)
            backend = self._write_checkpoint_files(path, client_state,
                                                   backend)
            if save_latest and jax.process_index() == 0:
                from deepspeed_tpu.runtime.resilience.atomic import \
                    write_latest

                write_latest(save_dir, tag, fsync=False)
            if jax.process_index() == 0 and res.keep_checkpoint_tags > 0:
                from deepspeed_tpu.runtime.resilience.atomic import gc_tags

                gc_tags(save_dir, res.keep_checkpoint_tags,
                        protect={str(tag)})
            log_dist(f"Saved checkpoint {path} (backend={backend}, "
                     f"non-atomic)", ranks=[0])
            if self._watchdog is not None:
                self._watchdog.heartbeat()
            self._ckpt_foreground_ms = (_time.perf_counter() - t0) * 1000.0
            self._publish_ckpt_metrics()
            return True

        from deepspeed_tpu.runtime.resilience.atomic import atomic_tag, \
            gc_tags

        meta = self._checkpoint_manifest_meta(tag)
        meta.update(manifest_meta or {})
        commit = atomic_tag(save_dir, tag, meta=meta,
                            update_latest=save_latest, fsync=res.fsync)
        if jax.process_count() > 1:
            # every process writes its shards into the same temp dir on the
            # shared FS; process 0 commits (manifest + rename) after a
            # barrier so no shard write races the rename.  Every phase
            # follows the coordination.all_agree discipline: swallow the
            # local error, agree on success flags, only then proceed or
            # raise — so no rank can leave peers wedged in a collective.
            from deepspeed_tpu.runtime.resilience.coordination import \
                all_agree

            def _agree(err, phase):
                agreed, n_failed = all_agree(err is None)
                if agreed:
                    return
                if err is not None:
                    raise err
                raise RuntimeError(
                    f"checkpoint {phase} for tag {tag!r} failed on "
                    f"{n_failed} peer process(es); "
                    f"tag aborted, previous checkpoint left intact")

            # process 0 alone creates the temp dir (its __enter__ rmtree's
            # any stale .tmp- from a prior crash); peers wait for the
            # agreement so that cleanup can never delete shards a peer has
            # already started writing
            enter_err = None
            if jax.process_index() == 0:
                try:
                    commit.__enter__()
                except BaseException as e:
                    enter_err = e
            _agree(enter_err, "temp-dir setup")
            write_err = None
            try:
                # peer makedirs sits INSIDE the agreed phase: a rank-local
                # mkdir failure must feed the agreement, not raise past it
                if jax.process_index() != 0:
                    os.makedirs(commit.tmp, exist_ok=True)
                backend = self._write_checkpoint_files(commit.tmp,
                                                       client_state, backend)
            except BaseException as e:
                write_err = e
            try:
                # the agreement doubles as the payload barrier: no shard
                # write can race the commit below
                _agree(write_err, "write")
            except BaseException as e:
                if jax.process_index() == 0:
                    commit.__exit__(type(e), e, e.__traceback__)
                raise
            commit_err = None
            if jax.process_index() == 0:
                try:
                    commit.meta["backend"] = backend
                    commit.__exit__(None, None, None)
                except BaseException as e:
                    commit_err = e
            _agree(commit_err, "commit")
        else:
            with commit as tmp:
                backend = self._write_checkpoint_files(tmp, client_state,
                                                       backend)
                commit.meta["backend"] = backend
        if jax.process_index() == 0 and res.keep_checkpoint_tags > 0:
            gc_tags(save_dir, res.keep_checkpoint_tags, protect={str(tag)})
        log_dist(f"Saved checkpoint {os.path.join(save_dir, str(tag))} "
                 f"(backend={backend}, atomic)", ranks=[0])
        if self._watchdog is not None:
            # a large fsync'd save legitimately takes minutes; don't let
            # the stall detector read it as a hung step
            self._watchdog.heartbeat()
        # a synchronous commit is ALL foreground — the honest comparison
        # number for the async path's rename-only foreground
        self._ckpt_foreground_ms = (_time.perf_counter() - t0) * 1000.0
        self._publish_ckpt_metrics()
        if self._tracer is not None:
            self._tracer.complete("ckpt_sync_commit", self._lane_ckpt, t0,
                                  a0=self.global_steps)
        return True

    def _ckpt_commit_heartbeat(self):
        """Heartbeat callable handed to the background commit thread:
        feeds the TrainingWatchdog (a slow disk is progress, not a
        stall) and — when tracing is armed — drops one instant event per
        fsync'd file on the ``ckpt`` lane, so the commit thread's
        progress renders in the exported trace."""
        wd_beat = self._watchdog.heartbeat if self._watchdog is not None \
            else None
        tr = self._tracer
        if wd_beat is None and tr is None:
            return None
        lane = self._lane_ckpt

        def beat():
            if wd_beat is not None:
                wd_beat()
            if tr is not None:
                tr.instant("ckpt_commit_beat", lane)

        return beat

    def _arm_async_commit(self, backend):
        """True when the async commit path can carry this save; otherwise
        warn DISARMED (naming every blocker) and fall back to the
        synchronous commit."""
        blockers = []
        if not self._resilience.atomic_checkpoints:
            blockers.append(
                "resilience.atomic_checkpoints=false (the legacy in-place "
                "layout has no seal/publish split to defer)")
        if self._resolve_ckpt_backend(backend) == "orbax":
            blockers.append(
                "orbax backend (its sharded writer gathers from live "
                "device state; backend='npz' snapshots to host first)")
        if blockers:
            log_dist(
                f"DeepSpeedEngine: async checkpoint commit DISARMED — "
                f"{'; '.join(blockers)}; committing synchronously",
                ranks=[0], level=logging.WARNING)
            return False
        return True

    def _publish_ckpt_metrics(self):
        """Mirror commit-path health into _last_metrics (satellite of the
        _last_metrics idiom): ckpt_commit_ms_foreground is the training-
        thread time of the last save (snapshot + rename legs for async,
        the whole commit for sync), ckpt_commit_pending flags an
        in-flight background seal."""
        self._ckpt_metrics = {
            "ckpt_commit_ms_foreground":
                round(getattr(self, "_ckpt_foreground_ms", 0.0), 3),
            "ckpt_commit_pending": int(self._pending_commit is not None),
        }
        if isinstance(self._last_metrics, dict):
            metrics = dict(self._last_metrics)
            metrics.update(self._ckpt_metrics)
            self._last_metrics = metrics

    def _finalize_pending_commit(self, wait=True):
        """Foreground leg of an async commit: the atomic rename +
        latest-pointer-last, then retention GC.  With wait=False (the
        per-step opportunistic call) an unfinished seal is left in
        flight.  Returns True when a commit was published.

        Multi-process follows the coordination.all_agree discipline:
        every rank waits for its local seal, all agree on success,
        process 0 alone publishes, and all agree again — a failed write
        on any rank aborts the tag everywhere with the previous
        checkpoint intact."""
        import time as _time

        import jax

        pending = self._pending_commit
        if pending is None:
            return False
        multi = jax.process_count() > 1
        if not wait:
            ready = pending.ready()
            if multi:
                # the publish involves collectives: every rank must take
                # it at the same step, so readiness itself is agreed
                from deepspeed_tpu.runtime.resilience.coordination import \
                    all_agree

                ready, _ = all_agree(ready)
            if not ready:
                return False
        info = self._pending_commit_info
        res = self._resilience
        t0 = _time.perf_counter()
        try:
            if multi:
                from deepspeed_tpu.runtime.resilience.coordination import \
                    all_agree

                pending.wait()
                agreed, n_failed = all_agree(pending.error is None)
                if not agreed:
                    if pending.error is not None:
                        pending.finalize()  # raises the local error
                    raise RuntimeError(
                        f"async checkpoint write for tag "
                        f"{info['tag']!r} failed on {n_failed} peer "
                        f"process(es); tag aborted, previous checkpoint "
                        f"left intact")
                commit_err = None
                try:
                    pending.finalize()  # FollowerCommit no-ops off-leader
                except BaseException as e:
                    commit_err = e
                agreed, n_failed = all_agree(commit_err is None)
                if commit_err is not None:
                    raise commit_err
                if not agreed:
                    raise RuntimeError(
                        f"async checkpoint publish for tag "
                        f"{info['tag']!r} failed on {n_failed} peer "
                        f"process(es)")
            else:
                pending.finalize()
        finally:
            self._pending_commit = None
            self._pending_commit_info = None
            self._ckpt_foreground_ms = \
                getattr(self, "_ckpt_foreground_ms", 0.0) \
                + (_time.perf_counter() - t0) * 1000.0
            self._publish_ckpt_metrics()
            if self._tracer is not None:
                self._tracer.complete("ckpt_publish", self._lane_ckpt, t0)
        from deepspeed_tpu.runtime.resilience import chaos
        from deepspeed_tpu.runtime.resilience.atomic import gc_tags

        # kill window between rename and GC: the tag is already durable
        # and visible — chaos proves auto-resume lands on it
        chaos.point("before_gc")
        if jax.process_index() == 0 and res.keep_checkpoint_tags > 0:
            gc_tags(info["save_dir"], res.keep_checkpoint_tags,
                    protect={info["tag"]})
        if self._watchdog is not None:
            self._watchdog.heartbeat()
        if self._supervisor is not None:
            # published-tag notification (ISSUE 13 async-cadence
            # satellite): the supervisor tracks only PUBLISHED tags as
            # rollback targets — a sealed-but-unpublished commit is not
            # durable-visible and must never be a recovery destination
            self._supervisor.on_commit_published(dict(info))
        log_dist(f"Committed async checkpoint "
                 f"{os.path.join(info['save_dir'], info['tag'])} "
                 f"(backend={info['backend']}, atomic)", ranks=[0])
        return True

    def wait_pending_commit(self):
        """Block until any in-flight async checkpoint commit is fully
        published (rename + latest + GC); True if one was.  Re-raises a
        failed background write (previous checkpoint left intact)."""
        return self._finalize_pending_commit(wait=True)

    def pending_commit(self):
        """True while an async checkpoint commit is still in flight."""
        return self._pending_commit is not None

    def load_checkpoint(self, load_dir, tag=None, load_module_strict=True,
                        load_optimizer_states=True,
                        load_lr_scheduler_states=True, auto_resume=None,
                        elastic=None):
        """Restore from ``load_dir``.

        tag=None loads the ``latest``-pointed tag.  With
        ``auto_resume=True`` (or resilience.auto_resume in ds_config) and
        ``tag=None``, the directory is scanned newest-first and
        corrupt/partial tags — failed manifest verification OR a
        load-time error — are skipped transparently until the newest
        intact checkpoint loads; returns (None, {}) when nothing intact
        exists.  An explicitly named tag is never second-guessed: it
        loads, or raises CheckpointCorrupt (never loads bad bytes
        silently, never substitutes a different tag).

        ``elastic=True`` makes a cross-topology restore explicit: the
        checkpoint's topology manifest is diffed against the live mesh
        (resilience/reshard.py), resharding actions are logged, schedule
        features the new topology drops DISARM-warn, the elastic batch
        config is verified against compute_elastic_config, and the
        returned client_state gains the reshard report + the exact data
        position (``data_position`` / ``micro_batches_to_skip``) so the
        sample stream resumes without replay.  Auto-resume is always
        elastic — a restart is exactly when the mesh may have changed."""
        from deepspeed_tpu.runtime.resilience import atomic as atomic_lib
        from deepspeed_tpu.runtime.resilience.atomic import CheckpointCorrupt

        # an in-flight async commit must land (or fail) before its tag can
        # be a resume candidate — and before a restore invalidates the
        # snapshot's meaning
        self._finalize_pending_commit(wait=True)
        res = self._resilience
        # a resumed run that aborts before its first save still has a
        # checkpoint home: the watchdog's emergency fallback dir
        self._last_ckpt_dir = self._last_ckpt_dir or load_dir
        if tag is not None:
            # an explicitly named tag is never second-guessed: it loads or
            # it raises; the newest-first scan is for tag=None only
            auto_resume = False
        elif auto_resume is None:
            auto_resume = res.auto_resume
        if auto_resume:
            # a restart is exactly when the topology may have changed;
            # elastic=False opts out explicitly
            return self._auto_resume_load(load_dir, load_module_strict,
                                          load_optimizer_states,
                                          load_lr_scheduler_states,
                                          elastic=elastic is not False)

        if tag is None:
            tag = atomic_lib.read_latest(load_dir)
            if tag is None:
                logger.warning(f"No 'latest' file at {load_dir}; nothing loaded")
                return None, {}
        if res.verify_on_load:
            import jax

            # leader-only verify + agreed verdict: N hosts re-hashing the
            # same multi-GB manifest multiplies load I/O by N, and a
            # rank-local verify failure must fail EVERY rank together —
            # one rank raising while peers enter the collective restore
            # would wedge the job (same discipline as save/auto-resume)
            from deepspeed_tpu.runtime.resilience.coordination import \
                all_agree

            if jax.process_index() == 0:
                ok, reason = atomic_lib.verify_tag(os.path.join(load_dir,
                                                                str(tag)))
            else:
                ok, reason = True, "verification failed on process 0"
            ok, _ = all_agree(ok)
            if not ok:
                raise CheckpointCorrupt(
                    f"checkpoint tag {tag!r} under {load_dir} failed "
                    f"verification: {reason}. Pass auto_resume=True to fall "
                    f"back to the newest intact checkpoint.")
        return self._load_checkpoint_tag(load_dir, tag, load_module_strict,
                                         load_optimizer_states,
                                         load_lr_scheduler_states,
                                         elastic=bool(elastic))

    def _auto_resume_load(self, load_dir, load_module_strict,
                          load_optimizer_states, load_lr_scheduler_states,
                          elastic=True):
        """Newest-first scan that falls back past corrupt/unloadable tags.

        Multi-process: process 0 alone selects each candidate (so every
        host attempts the SAME tag — per-host selection could send hosts
        into collective restores on different directories, a deadlock)
        and broadcasts it; after each attempt all hosts agree on success
        before returning, falling back together otherwise.  A failed
        attempt rolls the engine back to its pre-attempt state."""
        import jax

        from deepspeed_tpu.runtime.resilience import atomic as atomic_lib

        from deepspeed_tpu.runtime.resilience.coordination import \
            TAG_BCAST_BYTES, all_agree, broadcast_tag

        res = self._resilience
        multi = jax.process_count() > 1
        leader = jax.process_index() == 0
        cands = iter(atomic_lib.resume_candidates(load_dir)) \
            if (leader or not multi) else iter(())
        last_err = None
        while True:
            cand = None
            for c in cands:  # leader-side: next candidate passing verify
                if multi and len(str(c).encode()) > TAG_BCAST_BYTES:
                    logger.warning(f"auto-resume: skipping tag {c!r} "
                                   f"(name exceeds the {TAG_BCAST_BYTES}-"
                                   f"byte broadcast buffer)")
                    continue
                ok, reason = atomic_lib.verify_tag(
                    os.path.join(load_dir, c),
                    check_checksums=res.verify_on_load)
                if ok:
                    cand = c
                    break
                logger.warning(f"auto-resume: skipping tag {c!r} ({reason})")
            if multi:
                cand = broadcast_tag(cand)
            if cand is None:
                break
            # errors that cannot be tag-specific must fail loudly, not be
            # caught below as "corrupt tag" — the blanket catch would
            # reject every intact checkpoint and silently 'start fresh'
            # (state-built status is identical on every rank, so this
            # raises everywhere together)
            self._assert_loadable()
            snap = self._ckpt_state_snapshot()
            # any Exception means "this tag is bad" — the narrow whitelist
            # would let an unforeseen error (orbax XlaRuntimeError, tree
            # mismatch TypeError) escape without the rollback below, and on
            # multi-host without the agreement, wedging peers in the
            # collective (same discipline as the save path)
            err = None
            try:
                result = self._load_checkpoint_tag(
                    load_dir, cand, load_module_strict,
                    load_optimizer_states, load_lr_scheduler_states,
                    elastic=elastic)
            except Exception as e:
                err = e
            ok, _ = all_agree(err is None)
            if ok:
                return result
            # roll back everything _load_checkpoint_tag may have half-set:
            # "starting fresh" must not mean "corrupt params, stale opt"
            self._ckpt_state_restore(snap)
            if err is not None:
                last_err = err
                logger.warning(f"auto-resume: tag {cand!r} failed to load "
                               f"({type(err).__name__}: {err}); falling "
                               f"back to an older checkpoint")
            else:
                last_err = last_err or RuntimeError("peer load failure")
                logger.warning(f"auto-resume: a peer process failed to "
                               f"load tag {cand!r}; falling back together")
        if last_err is not None:
            logger.warning(f"auto-resume: no loadable checkpoint under "
                           f"{load_dir}; starting fresh")
        else:
            logger.warning(f"auto-resume: no checkpoint under "
                           f"{load_dir}; starting fresh")
        return None, {}

    def _ckpt_state_snapshot(self):
        """References/copies of everything _load_checkpoint_tag mutates
        (device state is immutable, so references suffice; host-side
        mutables are copied)."""
        import copy

        return {
            "state": self.state,
            "global_steps": self.global_steps,
            "micro_steps": self.micro_steps,
            "samples_skipped": self.samples_skipped,
            "onebit_latch": getattr(self, "_onebit_frozen_latch", False),
            "zeroone_latch": getattr(self, "_zeroone_frozen_latch", False),
            "host_master": getattr(self, "_host_master_flat", None),
            "host_opt": dict(self._host_opt)
            if getattr(self, "_host_opt", None) is not None else None,
            "host_skipped": getattr(self, "_host_skipped", None),
            "host_scale": self._host_scaler.cur_scale
            if getattr(self, "_host_scaler", None) is not None else None,
            "lr_sched": copy.deepcopy(self.lr_scheduler.state_dict())
            if self.lr_scheduler is not None else None,
        }

    def _discard_staged_micro(self):
        """Drop any in-flight forward() staging.  A recovery load must not
        leave a stale staged state behind: the next forward() would refuse
        ('called twice without backward') and backward() would commit
        pre-failure buffers over the freshly loaded checkpoint."""
        self._pending_state = None
        self._pending_loss = None
        self._pending_grads = None
        self._pending_s3_stash = None
        if getattr(self, "_pending_fetches", None):
            self._pending_fetches = []

    def _ckpt_state_restore(self, snap):
        # a rollback can land on the same global_steps with different
        # device counters — the host-side sync caches must not serve stale
        self._skipped_cache = None
        self._scale_cache = None
        self._discard_staged_micro()
        self.state = snap["state"]
        self.global_steps = snap["global_steps"]
        self.micro_steps = snap["micro_steps"]
        self.samples_skipped = snap["samples_skipped"]
        self._onebit_frozen_latch = snap["onebit_latch"]
        self._zeroone_frozen_latch = snap.get("zeroone_latch", False)
        if snap["host_master"] is not None:
            self._host_master_flat = snap["host_master"]
        if snap["host_opt"] is not None:
            self._host_opt.clear()
            self._host_opt.update(snap["host_opt"])
        if snap["host_skipped"] is not None:
            self._host_skipped = snap["host_skipped"]
        if snap["host_scale"] is not None:
            self._host_scaler.cur_scale = snap["host_scale"]
        if snap["lr_sched"] is not None and self.lr_scheduler is not None:
            self.lr_scheduler.load_state_dict(snap["lr_sched"])

    def _reset_misshaped_compression_state(self, host_state, ckpt_path):
        """Guard the npz restore against per-device compression state
        written on a different data axis.  The 1-bit/0-1 wire optimizers
        keep error-feedback residuals and a local-round accumulator with
        a leading (axis_size,) dim; a dp-change resume cannot remap old
        per-device error memories onto the new mesh, and device_put-ing
        the old-shaped arrays under the new shardings would silently
        misshape the TrainState (every jit retraces, then fails deep in
        shard_map).  Those leaves reset to zeros with a DISARMED warning
        — residuals are error *memory* and re-accumulate within a few
        rounds; any OTHER shape mismatch still fails loudly."""
        import jax

        _COMP_LEAVES = ("worker_error", "server_error", "local_accum")
        cur_flat = jax.tree_util.tree_flatten_with_path(self.state)[0]
        treedef = jax.tree_util.tree_structure(self.state)
        loaded = jax.tree_util.tree_leaves(host_state)
        out, reset = [], []
        for ((kpath, cur), old) in zip(cur_flat, loaded):
            name = jax.tree_util.keystr(kpath)
            if tuple(np.shape(old)) == tuple(cur.shape):
                out.append(old)
                continue
            if any(c in name for c in _COMP_LEAVES):
                out.append(np.zeros(cur.shape, np.asarray(old).dtype))
                reset.append(f"{name} {np.shape(old)} -> {cur.shape}")
            else:
                raise ValueError(
                    f"checkpoint at {ckpt_path} holds leaf {name} with "
                    f"shape {np.shape(old)} but the current engine "
                    f"expects {tuple(cur.shape)} — saved under a "
                    f"different config; re-save with the current version")
        if reset:
            log_dist(
                f"elastic resume: per-device compression state DISARMED "
                f"for this load — {len(reset)} error-feedback/accumulator "
                f"leaves were written on a different data axis and reset "
                f"to zero (they re-accumulate within a few rounds): "
                f"{'; '.join(reset[:4])}"
                + ("; ..." if len(reset) > 4 else ""),
                ranks=[0], level=logging.WARNING)
        return jax.tree_util.tree_unflatten(treedef, out)

    def _load_checkpoint_tag(self, load_dir, tag, load_module_strict=True,
                             load_optimizer_states=True,
                             load_lr_scheduler_states=True, elastic=False):
        import jax

        # imported here (not in the npz branch) because the offload restore
        # below needs it regardless of which backend saved the model state
        from deepspeed_tpu.runtime.checkpoint_utils import npz_dict_to_leaves

        path = os.path.join(load_dir, str(tag))
        with open(os.path.join(path, "metadata.pkl"), "rb") as f:
            meta = pickle.load(f)
        assert self.state is not None, \
            "call forward/train_batch once (or init_from_batch) before load_checkpoint"
        treedef = jax.tree_util.tree_structure(self.state)
        if meta.get("backend") == "orbax":
            import orbax.checkpoint as ocp

            # sharding-aware restore: orbax repartitions to the CURRENT
            # shardings, so world-size changes (elastic) need no gather
            sh_tree = jax.tree_util.tree_unflatten(
                treedef, jax.tree_util.tree_leaves(self._shardings))
            template = jax.tree_util.tree_map(
                lambda l, s: jax.ShapeDtypeStruct(l.shape, l.dtype,
                                                  sharding=s),
                self.state, sh_tree)
            ckptr = ocp.StandardCheckpointer()
            self.state = ckptr.restore(
                os.path.join(os.path.abspath(path), "orbax_state"),
                target=template)
        else:
            data = np.load(os.path.join(path, "model_states.npz"))
            flat = npz_dict_to_leaves(data)
            assert len(flat) == meta["num_leaves"]
            cur_leaves = len(jax.tree_util.tree_leaves(self.state))
            if len(flat) != cur_leaves:
                raise ValueError(
                    f"checkpoint at {path} holds {len(flat)} state leaves "
                    f"but this engine's TrainState has {cur_leaves} — the "
                    f"checkpoint was saved by an older engine revision or "
                    f"under a different config (e.g. pre-round-4 offload "
                    f"states carried a device grad accumulator); re-save "
                    f"with the current version")
            host_state = jax.tree_util.tree_unflatten(treedef, flat)
            host_state = self._reset_misshaped_compression_state(host_state,
                                                                 path)
            # re-shard onto the current mesh: elastic by construction — the
            # full arrays repartition to any world size (reference
            # stage1.py:1197-1255)
            sh_flat = jax.tree_util.tree_leaves(self._shardings)
            dev_flat = [jax.device_put(l, s) for l, s in
                        zip(jax.tree_util.tree_leaves(host_state), sh_flat)]
            self.state = jax.tree_util.tree_unflatten(treedef, dev_flat)

        if self._offload:
            off = np.load(os.path.join(path, "offload_states.npz"))
            leaves = npz_dict_to_leaves(off)
            n = len(self._host_master_flat)
            assert len(leaves) == 3 * n
            # np.array(copy=True): loaded npz views can be read-only and
            # the host Adam updates these buffers in place
            self._host_master_flat = [np.array(l, copy=True)
                                      for l in leaves[:n]]
            self._host_opt["m"] = [np.array(l, copy=True)
                                   for l in leaves[n:2 * n]]
            self._host_opt["v"] = [np.array(l, copy=True)
                                   for l in leaves[2 * n:]]
            self._host_opt["step"] = int(off["opt_step"])
            # host-side skip counter: meta holds device + host total; the
            # device part restored with the state leaves above
            device_skips = int(jax.device_get(self.state.skipped_steps))
            self._host_skipped = max(
                0, int(meta.get("skipped_steps", 0)) - device_skips)
            if self._host_scaler is not None and self.state.scaler is not None:
                self._host_scaler.cur_scale = float(
                    jax.device_get(self.state.scaler.loss_scale))

        self.global_steps = meta["global_steps"]
        self.micro_steps = meta["micro_steps"]
        # skipped-data bias (ISSUE 13 rollback-and-skip): restore the
        # stream offset the tag recorded — a resume must fast-forward
        # past both the trained AND the deliberately skipped samples
        from deepspeed_tpu.runtime.resilience import reshard as _reshard

        self.samples_skipped = int(
            (meta.get(_reshard.DATA_POSITION_KEY) or {})
            .get("samples_skipped", 0))
        # the 1-bit freeze phase latches on optimizer steps; a rollback to a
        # pre-freeze tag must re-derive it from the restored counters, not
        # keep serving the compressed program through what is warmup again
        self._onebit_frozen_latch = False
        self._zeroone_frozen_latch = False
        # loaded device counters invalidate the host-side sync caches (the
        # loaded tag may share global_steps with the pre-load state), and
        # any staged micro-batch from before the load is dead weight
        self._skipped_cache = None
        self._scale_cache = None
        self._discard_staged_micro()
        # skipped_steps restores with the device state (a TrainState leaf)
        if load_lr_scheduler_states and self.lr_scheduler is not None \
                and meta.get("lr_scheduler") is not None:
            self.lr_scheduler.load_state_dict(meta["lr_scheduler"])
        log_dist(f"Loaded checkpoint {path} (saved at dp={meta['dp_world_size']}, "
                 f"now dp={self.dp_world_size})", ranks=[0])
        if self._watchdog is not None:
            # mid-run restores can take minutes; not a stalled step
            self._watchdog.heartbeat()
        return path, self._elastic_client_state(meta, elastic)

    def _elastic_client_state(self, meta, elastic):
        """client_state returned by a load, with the elastic reshard
        report + exact data position attached when the load was elastic.
        A non-elastic cross-topology load still works (the payloads are
        topology-independent) but gets one info line pointing at
        elastic=True instead of the full plan."""
        from deepspeed_tpu.runtime.resilience import reshard

        client = dict(meta.get("client_state") or {})
        if elastic:
            report = reshard.elastic_load_report(meta, self)
            client["elastic_reshard"] = report
            client.setdefault(reshard.DATA_POSITION_KEY,
                              meta.get(reshard.DATA_POSITION_KEY))
        else:
            saved = (meta.get(reshard.TOPOLOGY_KEY) or {})
            if saved.get("dp") not in (None, self.dp_world_size):
                log_dist(
                    f"checkpoint was written at dp={saved.get('dp')}, now "
                    f"dp={self.dp_world_size}; pass elastic=True to "
                    f"load_checkpoint for the verified reshard plan + "
                    f"data-position resume", ranks=[0])
        return client

    def init_from_batch(self, batch):
        """Explicitly build train state from a sample batch (e.g. before
        load_checkpoint without training first)."""
        self._ensure_state(batch)
        self._compile()


def _tree_has_deleted(tree, first_only=False):
    """True if (any of / the first of) the pytree's jax arrays has had its
    buffer deleted — the donated-then-failed signature.  ``first_only``
    keeps the per-micro-step check O(1): donation invalidates every donated
    input at dispatch, so one leaf is representative."""
    import jax

    for leaf in jax.tree_util.tree_leaves(tree):
        is_deleted = getattr(leaf, "is_deleted", None)
        if callable(is_deleted):
            try:
                if is_deleted():
                    return True
            except Exception:  # pragma: no cover - defensive: liveness
                return True    # probe failing means the buffer is unusable
            if first_only:
                return False
    return False


def _leaf_path_names(tree):
    """'/'-joined pytree path of every leaf, in flatten order — the leaf
    naming shared by the stage-3 gather plan (block grouping) and the
    comm-accounting leaf specs, so the two can never drift."""
    import jax

    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    names = []
    for path, _leaf in flat:
        parts = [str(getattr(p, "key", getattr(p, "idx",
                                               getattr(p, "name", p))))
                 for p in path]
        names.append("/".join(parts) or "param")
    return names


def _spec_data_dim(sh):
    """Dim index a NamedSharding's PartitionSpec puts 'data' on (None =
    replicated over the data axis)."""
    for d, axis in enumerate(sh.spec):
        axes = axis if isinstance(axis, tuple) else (axis,)
        if axis is not None and "data" in axes:
            return d
    return None


def _stack_batches(micros):
    return {k: np.stack([np.asarray(m[k]) for m in micros]) for k in micros[0]} \
        if isinstance(micros[0], dict) else np.stack([np.asarray(m) for m in micros])


def _first_micro(batch):
    return _micro_at(batch, 0)


def _micro_at(batch, i):
    if isinstance(batch, dict):
        return {k: v[i] for k, v in batch.items()}
    return batch[i]
