"""Runtime helper utilities.

TPU-native analogs of reference deepspeed/runtime/utils.py: balanced layer
partitioning (:311-377 partition_uniform/partition_balanced), PartitionedTensor
(:395), overflow checking (:63-133), norm helpers (:170-294), memory reporting
(:547).  Partitioning is pure Python; tensor ops are jnp.
"""
import math

import numpy as np

from deepspeed_tpu.utils.logging import logger


# ---------------------------------------------------------------------------
# Layer partitioning (pure python; used by PipelineModule)
# ---------------------------------------------------------------------------

def ensure_directory_exists(filename):
    import os
    dirname = os.path.dirname(filename)
    if dirname:
        os.makedirs(dirname, exist_ok=True)


def partition_uniform(num_items: int, num_parts: int):
    """Split ``num_items`` into ``num_parts`` contiguous chunks as evenly as possible.

    Returns a list of ``num_parts + 1`` boundaries: part p owns
    ``[parts[p], parts[p+1])``.
    """
    parts = [0] * (num_parts + 1)
    if num_items <= num_parts:
        for p in range(num_parts + 1):
            parts[p] = min(p, num_items)
        return parts
    chunksize = num_items // num_parts
    residual = num_items % num_parts
    # the first `residual` parts get one extra item
    parts = [p * chunksize + min(p, residual) for p in range(num_parts + 1)]
    return parts


def prefix_sum_inc(weights):
    """Inclusive prefix sum."""
    out = list(weights)
    for i in range(1, len(out)):
        out[i] += out[i - 1]
    return out


def _lprobe(weights, num_parts, bottleneck):
    """Greedy probe: can ``weights`` be split into num_parts chains each <= bottleneck?"""
    num_items = len(weights)
    total_weight = weights[-1]
    parts = [0] * (num_parts + 1)

    bsum = bottleneck
    chunksize = num_items // num_parts
    step = chunksize
    for p in range(1, num_parts):
        while step < num_items and weights[step] < bsum:
            step += chunksize
        idx = int(np.searchsorted(weights[max(0, step - chunksize):step], bsum)) + \
            max(0, step - chunksize)
        if idx >= num_items:
            parts[p:num_parts] = [num_items] * (num_parts - p)
            break
        parts[p] = idx
        bsum = weights[idx - 1] + bottleneck if idx > 0 else bottleneck
    parts[num_parts] = num_items
    success = bsum >= total_weight
    return parts, success


def _rb_partition_balanced(weights, num_parts, eps):
    """Binary search over the bottleneck value."""
    total = weights[-1]
    lower = total / num_parts
    upper = total
    while upper > lower + eps:
        mid = lower + (upper - lower) / 2
        _, success = _lprobe(weights, num_parts, mid)
        if success:
            upper = mid
        else:
            lower = mid
    return upper


def partition_balanced(weights, num_parts, eps=1e-3):
    """Partition items with the given weights into parts minimizing the max part
    weight (binary search over bottleneck + greedy probe), as in reference
    runtime/utils.py:326-375."""
    num_items = len(weights)
    if num_items <= num_parts:
        return partition_uniform(num_items, num_parts)
    weights_ = prefix_sum_inc(weights)
    bottleneck = _rb_partition_balanced(weights_, num_parts, eps=eps)
    parts, success = _lprobe(weights_, num_parts, bottleneck + eps)
    assert success
    return parts


# ---------------------------------------------------------------------------
# Tensor helpers (jnp)
# ---------------------------------------------------------------------------

class PartitionedTensor:
    """Shard a flat tensor across a mesh axis; ``full()`` re-materializes.

    Functional analog of reference runtime/utils.py:395-505.  Used by the
    pipeline engine to send model-parallel-partitioned activations.  Inside jit
    use :func:`partition_and_slice` / :func:`gather_full` directly; this object
    wrapper serves host-level code and tests.
    """

    def __init__(self, tensor, axis_size: int, axis_index: int):
        import jax.numpy as jnp

        self.orig_shape = tuple(tensor.shape)
        self.orig_size = int(np.prod(self.orig_shape))
        self.axis_size = axis_size
        self.axis_index = axis_index
        flat = jnp.ravel(tensor)
        padded = self.padded_size(self.orig_size, axis_size)
        if padded != self.orig_size:
            flat = jnp.pad(flat, (0, padded - self.orig_size))
        self.part_size = padded // axis_size
        self.local_data = flat[axis_index * self.part_size:(axis_index + 1) * self.part_size]

    @staticmethod
    def padded_size(numel: int, parts: int) -> int:
        return math.ceil(numel / parts) * parts

    def to_meta(self):
        return {"orig_shape": self.orig_shape, "orig_size": self.orig_size,
                "axis_size": self.axis_size, "part_size": self.part_size}

    @classmethod
    def from_parts(cls, parts_list, meta):
        import jax.numpy as jnp

        obj = cls.__new__(cls)
        obj.orig_shape = tuple(meta["orig_shape"])
        obj.orig_size = meta["orig_size"]
        obj.axis_size = meta["axis_size"]
        obj.part_size = meta["part_size"]
        obj.local_data = jnp.concatenate([jnp.ravel(p) for p in parts_list])
        return obj

    def data(self):
        return self.local_data

    def full(self, gathered_parts=None):
        """Reassemble; outside jit the caller provides all parts."""
        import jax.numpy as jnp

        if gathered_parts is None:
            gathered_parts = [self.local_data]
            assert self.axis_size == 1
        flat = jnp.concatenate([jnp.ravel(p) for p in gathered_parts])
        return flat[:self.orig_size].reshape(self.orig_shape)


def global_norm_from_tree(grads, ord=2):
    """L2 norm over a pytree of arrays (the reference computes this per
    partition with cross-group allreduce; under GSPMD psum is implicit)."""
    import jax
    import jax.numpy as jnp

    leaves = jax.tree_util.tree_leaves(grads)
    if not leaves:
        return jnp.zeros(())
    return jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in leaves))


def has_overflow(grads):
    """True if any grad contains inf/nan (reference CheckOverflow, utils.py:63-133).
    Under pjit the reduction is global automatically."""
    import jax
    import jax.numpy as jnp

    leaves = jax.tree_util.tree_leaves(grads)
    finite = jnp.asarray(True)
    for g in leaves:
        finite = jnp.logical_and(finite, jnp.all(jnp.isfinite(g)))
    return jnp.logical_not(finite)


def clip_grad_by_global_norm(grads, max_norm, global_norm=None):
    import jax
    import jax.numpy as jnp

    if global_norm is None:
        global_norm = global_norm_from_tree(grads)
    scale = jnp.minimum(1.0, max_norm / (global_norm + 1e-6))
    return jax.tree_util.tree_map(lambda g: (g.astype(jnp.float32) * scale).astype(g.dtype),
                                  grads), global_norm


def see_memory_usage(message, force=False):
    """Log per-device HBM usage — a thin delegate over the ONE
    memory_stats() normalizer (runtime/memory_accounting.py), so every
    probe in the repo renders the same per-backend variants the same
    way (None on CPU = silently no line, never a crash)."""
    if not force:
        return
    from deepspeed_tpu.runtime.memory_accounting import \
        device_memory_report

    lines = [message]
    for entry in device_memory_report():
        if entry["bytes_in_use"] is None:
            continue
        lines.append(
            f"  {entry['kind']}:{entry['id']}: "
            f"in_use={(entry['bytes_in_use'] or 0)/2**30:.2f}GB "
            f"peak={(entry['peak_bytes_in_use'] or 0)/2**30:.2f}GB "
            f"limit={(entry['bytes_limit'] or 0)/2**30:.2f}GB")
    logger.info("\n".join(lines))


def opt_shardings_by_shape(flat_opt, param_shapes, flat_param_sh, rep):
    """Fallback sharding for client-optimizer state leaves (optimizers
    without ``state_spec``): scalars replicate; a param-shaped leaf takes the
    sharding of the same-shaped param **only when that mapping is
    unambiguous** — if two params share a shape but carry different
    shardings, the leaf replicates (correct, just not partitioned) instead of
    silently inheriting whichever param flattened first.

    Shared by DeepSpeedEngine._build_shardings and the pipeline engine's
    per-stage variant. Implement ``state_spec()`` on the optimizer for exact
    per-param placement.
    """
    by_shape = {}
    ambiguous = set()
    for shp, sh in zip(param_shapes, flat_param_sh):
        if shp in by_shape and by_shape[shp] != sh:
            ambiguous.add(shp)
        by_shape.setdefault(shp, sh)
    for shp in ambiguous:
        logger.warning(
            f"optimizer-state sharding fallback: params of shape {shp} have "
            f"conflicting shardings; replicating matching state leaves "
            f"(define optimizer.state_spec() for exact placement)")
        by_shape[shp] = rep
    return [rep if leaf.ndim == 0 else by_shape.get(tuple(leaf.shape), rep)
            for leaf in flat_opt]
