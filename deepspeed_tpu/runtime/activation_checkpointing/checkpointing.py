"""Activation checkpointing — recompute-instead-of-save, TPU-native.

Reference behavior: deepspeed/runtime/activation_checkpointing/
checkpointing.py:58-832 (CheckpointFunction with partitioned/CPU/contiguous
activations, model-parallel RNG tracker, configure()/is_configured()).

TPU formulation: `checkpoint(fn, *args)` wraps `jax.checkpoint` — XLA
rematerializes inside the jitted step, which subsumes the reference's manual
save/recompute machinery:
- partition_activations -> saved residuals inherit GSPMD shardings, so they
  are already partitioned across the mesh; the flag additionally selects the
  nothing-saveable policy (recompute everything, the most memory-lean);
- checkpoint_in_cpu -> offload saved residuals to host memory via
  jax.checkpoint policies (offload_dot_products...) where supported;
- contiguous_checkpointing -> no-op (XLA owns layout; accepted for config
  parity);
- model-parallel RNG: `model_parallel_rng(key)` folds the mesh 'model'
  coordinate into the key so dropout differs per TP shard, the analog of the
  reference's CudaRNGStatesTracker branch seeds (:148-263).
"""
from typing import Any, Optional

from deepspeed_tpu.utils.logging import logger

# module state (reference keeps the same globals, :40-56)
_CONFIG = {
    "partition_activations": False,
    "contiguous_checkpointing": False,
    "checkpoint_in_cpu": False,
    "synchronize": False,
    "profile": False,
    "num_checkpoints": None,
}
_CONFIGURED = False
_MPU = None
_NUM_LAYERS = None


def configure(mpu_=None, deepspeed_config=None, partition_activations=None,
              contiguous_checkpointing=None, checkpoint_in_cpu=None,
              synchronize=None, profile=None, num_checkpoints=None):
    """Reference analog: checkpointing.py:747-827. Accepts either explicit
    flags or a DeepSpeedConfig(-like) object / path with an
    activation_checkpointing section."""
    global _CONFIGURED, _MPU, _NUM_LAYERS
    _CONFIGURED = True
    _MPU = mpu_

    if deepspeed_config is not None:
        from deepspeed_tpu.runtime.config import DeepSpeedConfig

        cfg = deepspeed_config
        if isinstance(cfg, (str, dict)):
            cfg = DeepSpeedConfig(cfg, world_size=1)
        ac = getattr(cfg, "activation_checkpointing_config", None)
        if ac is not None:
            _CONFIG["partition_activations"] = ac.partition_activations
            _CONFIG["contiguous_checkpointing"] = \
                ac.contiguous_memory_optimization
            _CONFIG["checkpoint_in_cpu"] = ac.cpu_checkpointing
            _CONFIG["synchronize"] = ac.synchronize_checkpoint_boundary
            _CONFIG["profile"] = ac.profile
            _NUM_LAYERS = ac.number_checkpoints

    for key, val in [("partition_activations", partition_activations),
                     ("contiguous_checkpointing", contiguous_checkpointing),
                     ("checkpoint_in_cpu", checkpoint_in_cpu),
                     ("synchronize", synchronize), ("profile", profile)]:
        if val is not None:
            _CONFIG[key] = val
    if num_checkpoints is not None:
        _NUM_LAYERS = num_checkpoints
    if _CONFIG["contiguous_checkpointing"]:
        logger.info("contiguous_checkpointing: XLA owns buffer layout on "
                    "TPU; flag accepted for parity and otherwise ignored")
    if _CONFIG["contiguous_checkpointing"] and _NUM_LAYERS is None:
        raise ValueError(
            "contiguous_checkpointing requires num_checkpoints "
            "(reference checkpointing.py:816-818)")


def is_configured():
    return _CONFIGURED


def reset():
    """Reference analog: :691-703 (frees contiguous buffers there; clears
    config state here)."""
    global _CONFIGURED, _NUM_LAYERS
    _CONFIGURED = False
    _NUM_LAYERS = None
    for k, v in [("partition_activations", False),
                 ("contiguous_checkpointing", False),
                 ("checkpoint_in_cpu", False), ("synchronize", False),
                 ("profile", False)]:
        _CONFIG[k] = v


def partition_activations_in_checkpoint(flag):
    """Reference analog: :678-683."""
    _CONFIG["partition_activations"] = flag
    logger.info(f"**************Partition Activations {flag}************")


def set_num_layers(nlayers):
    global _NUM_LAYERS
    _NUM_LAYERS = nlayers


def _policy():
    import jax

    if _CONFIG["checkpoint_in_cpu"]:
        # save matmul outputs but offload them to host memory — the TPU
        # analog of cpu_checkpointing's activation host placement
        try:
            return jax.checkpoint_policies.offload_dot_products_with_no_batch_dims(
                "device", "pinned_host")
        except AttributeError:  # older jax
            logger.warning("checkpoint_in_cpu: offload policy unavailable "
                           "in this jax; falling back to full recompute")
            return jax.checkpoint_policies.nothing_saveable
    if _CONFIG["partition_activations"]:
        return jax.checkpoint_policies.nothing_saveable
    # default matches torch checkpointing: save boundaries, recompute body
    return None


def checkpoint(function, *args):
    """Checkpoint a function call: outputs computed normally, intermediate
    activations rematerialized in backward (reference CheckpointFunction,
    :362-663). Differentiable; non-array args are captured statically."""
    import jax

    policy = _policy()
    wrapped = jax.checkpoint(function, policy=policy) if policy is not None \
        else jax.checkpoint(function)
    return wrapped(*args)


# ---------------------------------------------------------------------------
# model-parallel RNG (reference CudaRNGStatesTracker :148-263)
# ---------------------------------------------------------------------------
_MODEL_PARALLEL_RNG_TRACKER_NAME = "model-parallel-rng"


def model_parallel_rng(key, axis_name: str = "model"):
    """Per-TP-shard dropout key: fold the mesh coordinate into the key.
    Inside jit/shard_map with the axis bound, each model-parallel shard
    draws independent dropout masks (the reference tracker's
    model-parallel-rng branch seed = base + 2718 + rank, :238-248)."""
    import jax

    try:
        idx = jax.lax.axis_index(axis_name)
    except NameError:
        return key
    return jax.random.fold_in(key, 2718 + idx)


class RNGStatesTracker:
    """Named RNG streams over jax keys (reference :148-214). States are
    explicit keys rather than device RNG registers; `fork(name)` returns a
    fresh key from the named stream and advances it."""

    def __init__(self):
        self.states = {}

    def reset(self):
        self.states = {}

    def get_states(self):
        return dict(self.states)

    def set_states(self, states):
        self.states = dict(states)

    def add(self, name, seed):
        import jax

        if name in self.states:
            raise Exception(f"rng state {name} already exists")
        self.states[name] = jax.random.PRNGKey(seed)

    def fork(self, name=_MODEL_PARALLEL_RNG_TRACKER_NAME):
        import jax

        if name not in self.states:
            raise Exception(f"rng state {name} is not added")
        self.states[name], out = jax.random.split(self.states[name])
        return out


_RNG_TRACKER = RNGStatesTracker()


def get_rng_tracker():
    return _RNG_TRACKER


# torch-API alias (reference get_cuda_rng_tracker)
get_cuda_rng_tracker = get_rng_tracker


def model_parallel_seed(seed, model_parallel_rank=0):
    """Seed the default + model-parallel streams (reference
    model_parallel_cuda_manual_seed :224-263)."""
    offset = seed + 2718
    _RNG_TRACKER.reset()
    _RNG_TRACKER.add("default", seed)
    _RNG_TRACKER.add(_MODEL_PARALLEL_RNG_TRACKER_NAME,
                     offset + model_parallel_rank)


model_parallel_cuda_manual_seed = model_parallel_seed
