"""Error-compensated compressed collectives — TPU-native 1-bit allreduce.

Reference behavior (deepspeed/runtime/fp16/onebit_adam.py:104-228 +
runtime/custom_collectives.py:10-152): each worker adds its error-feedback
residual, sign-compresses (scale = ||x||_2/sqrt(n), sign with 0 -> +1),
scatters chunk j to "server" j; each server averages the w compressed chunks,
re-compresses with its own residual, and all-gathers the result.

Here the same two-phase scheme runs *inside one jitted step* over a named mesh
axis: `lax.all_to_all` is the worker->server scatter-gather, `lax.all_gather`
broadcasts the server result, and signs travel bit-packed in uint8 (32x less
traffic than fp32 — the same wire format the reference gets from
cupy.packbits). mpi4py/cupy stream juggling disappears; XLA schedules the
collectives on ICI/DCN.
"""
import jax
import jax.numpy as jnp
from jax import lax

_POW2 = jnp.asarray([128, 64, 32, 16, 8, 4, 2, 1], dtype=jnp.uint8)


def pack_signs(signs):
    """{-1,+1} float vector (len % 8 == 0) -> uint8 bit-packed vector."""
    bits = (signs > 0).astype(jnp.uint8).reshape(-1, 8)
    return (bits * _POW2[None, :]).sum(-1).astype(jnp.uint8)


def unpack_signs(packed):
    """uint8 bit-packed vector -> {-1,+1} float32 vector."""
    bits = (packed[:, None] // _POW2[None, :]) % 2
    return (bits.astype(jnp.float32) * 2.0 - 1.0).reshape(-1)


def _sign_compress(x):
    """Returns (scale, signs, residual): x ~= scale*signs, residual = x - that.

    scale = ||x||_2 / sqrt(n) (reference onebit_adam.py:123); sign(0) -> +1
    (the reference's sign().add_(1).bool() mapping, onebit_adam.py:124-127).
    """
    scale = jnp.linalg.norm(x) / jnp.sqrt(jnp.float32(x.size))
    signs = jnp.where(x >= 0, 1.0, -1.0).astype(jnp.float32)
    return scale, signs, x - scale * signs


def compressed_allreduce(x, worker_error, server_error, axis_name):
    """Error-compensated 1-bit average of per-device `x` over `axis_name`.

    Must be called inside shard_map/pmap with `axis_name` bound. `x` is the
    device-local flat fp32 tensor, length divisible by 8*axis_size; ``x.size
    == worker_error.size``; ``server_error`` is either chunk-sized
    (x.size // axis_size, this device's server residual) or full-sized
    (x.size — this device's chunk is sliced at axis_index and written back,
    so optimizer state stays param-shaped).

    Returns (averaged_x, new_worker_error, new_server_error).
    """
    w = lax.axis_size(axis_name)
    n = x.size
    assert n % (8 * w) == 0, f"compressed_allreduce needs size % {8*w} == 0, got {n}"
    full_server_error = server_error.size == n
    if full_server_error:
        idx = lax.axis_index(axis_name)
        server_error_full = server_error
        server_error = lax.dynamic_slice(server_error, (idx * (n // w),),
                                         (n // w,))

    # --- worker phase: compensate, compress, scatter chunks to servers ----
    buf = x + worker_error
    worker_scale, signs, new_worker_error = _sign_compress(buf)
    packed = pack_signs(signs).reshape(w, n // (8 * w))
    # chunk j of every worker lands on device j: rows = per-worker signs of my chunk
    recv = lax.all_to_all(packed, axis_name, split_axis=0, concat_axis=0,
                          tiled=False)
    scales = lax.all_gather(worker_scale, axis_name)             # (w,)
    if recv.ndim == 1:  # w == 1 keeps the row dim collapsed
        recv = recv.reshape(w, -1)
    worker_signs = unpack_signs(recv.reshape(-1)).reshape(w, n // w)

    # --- server phase: average, re-compress with server residual ---------
    server_m = (worker_signs * scales[:, None]).sum(0) / w + server_error
    server_scale, server_signs, new_server_error = _sign_compress(server_m)
    server_packed = pack_signs(server_signs)

    # --- broadcast: all-gather every server's compressed chunk -----------
    all_packed = lax.all_gather(server_packed, axis_name)        # (w, n/8w)
    all_scales = lax.all_gather(server_scale, axis_name)         # (w,)
    out_signs = unpack_signs(all_packed.reshape(-1)).reshape(w, n // w)
    out = (out_signs * all_scales[:, None]).reshape(-1)
    if full_server_error:
        new_server_error = lax.dynamic_update_slice(
            server_error_full, new_server_error, (idx * (n // w),))
    return out, new_worker_error, new_server_error


def quantized_reduce_scatter(x, axis_name, *, dim=0,
                             block_size=None, intra_size=0):
    """qgZ: mean-reduce-scatter of per-device ``x`` over ``axis_name`` with
    blockwise-int8 wire format (ZeRO++ arxiv 2306.10209 §4.3).

    Must run inside shard_map with ``axis_name`` manual.  ``x`` is the
    device-local (full-shape) tensor; ``x.shape[dim]`` must divide the axis
    size ``w``.  Returns this device's shard of ``mean_over_axis(x)`` along
    ``dim`` (shape ``x.shape`` with dim -> dim/w): the exact output a dense
    fp32 reduce-scatter would produce, at ~1/4 the wire bytes.

    Flat scheme (intra_size in {0, 1, w}): quantize the w destination chunks
    -> all_to_all int8 + fp32 scales -> dequantize -> local mean.

    Hierarchical scheme (1 < intra_size < w, intra_size | w): the ZeRO++ qgZ
    two-hop.  Ranks are grouped [0..k-1], [k..2k-1], ... (the mesh builder
    lays 'data' out so consecutive ranks share the fastest links).  Hop 1:
    all_to_all WITHIN each group of k, local partial sum — after it each rank
    holds 1/k of the data, reduced over its group.  Hop 2: all_to_all ACROSS
    groups (ranks with equal intra index) on re-quantized partial sums —
    cross-group (DCN on a multi-slice TPU) traffic drops to 1/k of the flat
    scheme.  Both hops move int8 + per-block scales.

    Overflow safety: non-finite inputs produce non-finite block scales
    (quantization.py), so the dequantized mean is non-finite and the
    engine's loss-scale check still trips.
    """
    from deepspeed_tpu.runtime.quantization import (DEFAULT_BLOCK_SIZE,
                                                    dequantize_rows,
                                                    quantize_rows)

    if block_size is None:
        block_size = DEFAULT_BLOCK_SIZE
    w = lax.axis_size(axis_name)
    s_d = x.shape[dim]
    assert s_d % w == 0, \
        f"quantized_reduce_scatter: dim {dim} (size {s_d}) must divide the " \
        f"axis size {w}"
    moved = jnp.moveaxis(x, dim, 0)
    rest = moved.shape[1:]
    rows = moved.reshape(w, -1)          # row r = final shard of rank r
    nloc = rows.shape[1]

    k = int(intra_size or 0)
    if not (1 < k < w and w % k == 0):
        k = 0

    if not k:
        q, scales = quantize_rows(rows, block_size)
        qr = lax.all_to_all(q, axis_name, 0, 0, tiled=False)
        sr = lax.all_to_all(scales, axis_name, 0, 0, tiled=False)
        if qr.ndim == 1:                 # w == 1 collapses the row dim
            qr, sr = qr[None], sr[None]
        total = dequantize_rows(qr, sr, nloc).sum(0)
    else:
        m = w // k
        groups_intra = [[o * k + i for i in range(k)] for o in range(m)]
        groups_inter = [[o * k + i for o in range(m)] for i in range(k)]
        # hop 1: row r = o_dest*k + i_dest; regroup so the k pieces sent
        # within my group are keyed by destination INTRA index
        x1 = rows.reshape(m, k, nloc).transpose(1, 0, 2).reshape(k, -1)
        q1, s1 = quantize_rows(x1, block_size)
        qr1 = lax.all_to_all(q1, axis_name, 0, 0, tiled=False,
                             axis_index_groups=groups_intra)
        sr1 = lax.all_to_all(s1, axis_name, 0, 0, tiled=False,
                             axis_index_groups=groups_intra)
        partial = dequantize_rows(qr1, sr1, m * nloc).sum(0)   # my intra chunk
        # hop 2: split my group-reduced 1/k across the m outer ranks
        q2, s2 = quantize_rows(partial.reshape(m, nloc), block_size)
        qr2 = lax.all_to_all(q2, axis_name, 0, 0, tiled=False,
                             axis_index_groups=groups_inter)
        sr2 = lax.all_to_all(s2, axis_name, 0, 0, tiled=False,
                             axis_index_groups=groups_inter)
        total = dequantize_rows(qr2, sr2, nloc).sum(0)

    out = (total / w).reshape((s_d // w,) + rest)
    return jnp.moveaxis(out, 0, dim)


def quantized_all_gather(x, mesh, *, dim=0, axis_name="data",
                         block_size=None, out_dtype=None):
    """qwZ: materialize a ZeRO-sharded parameter leaf replicated, moving
    blockwise-int8 + per-block fp32 scales on the wire (ZeRO++ arxiv
    2306.10209 §4.1) — the scheduled-stage-3 sibling of
    :func:`quantized_reduce_scatter`.

    ``x`` is the GLOBAL full-shape array whose ``dim`` is sharded over
    ``axis_name`` of ``mesh`` (``x.shape[dim]`` must divide the axis
    size), called inside a jit under the engine mesh.  The quantize ->
    gather -> dequantize core runs inside a leaf-level ``shard_map``
    with ``axis_name`` manual, so the collective is an EXPLICIT
    ``lax.all_gather`` of the int8 blocks and fp32 scales.  This is
    load-bearing: a GSPMD formulation (quantize, then
    sharding-constrain the int8 replicated) leaves the partitioner free
    to satisfy the constraint by gathering the fp32 values first and
    quantizing replicated — the wire silently fattens back to full
    precision.  Manual-mode collectives pin the payload dtype the same
    way the qgZ all_to_alls do (s8 in the compiled HLO, the only wire
    dtype that survives XLA's convert-commuting and the CPU backend's
    bf16 legalization).

    Differentiable with a straight-through vjp: the cotangent passes
    through the quantizer unchanged (``round`` has zero derivative — the
    true vjp would silently zero every gradient) and is constrained back
    onto the ZeRO shard layout, so XLA lowers the gradient path to a
    reduce-scatter into the sharded accumulator with no dense
    all-reduce.

    Overflow safety matches the other quantized wires: non-finite
    shard values produce non-finite block scales, so the gathered
    weights come back non-finite and the loss-scale check still trips.
    """
    from jax.sharding import PartitionSpec as P

    from deepspeed_tpu.parallel.mesh import constrain
    from deepspeed_tpu.runtime.quantization import (DEFAULT_BLOCK_SIZE,
                                                    dequantize_rows,
                                                    quantize_rows)

    if block_size is None:
        block_size = DEFAULT_BLOCK_SIZE
    w = int(mesh.shape[axis_name])
    out_dtype = out_dtype or x.dtype
    if w <= 1:
        return x.astype(out_dtype)
    shape = x.shape
    s_d = shape[dim]
    assert s_d % w == 0, \
        f"quantized_all_gather: dim {dim} (size {s_d}) must divide the " \
        f"'{axis_name}' axis size {w}"
    nloc = x.size // w
    in_dtype = x.dtype
    shard_spec = P(*([None] * dim + [axis_name]
                     + [None] * (x.ndim - dim - 1)))
    moved_shape = (s_d,) + shape[:dim] + shape[dim + 1:]

    def body(local):
        # local: this rank's shard, shape[dim] -> s_d/w
        rows = jnp.moveaxis(local, dim, 0).reshape(1, nloc)
        q, scales = quantize_rows(rows, block_size)
        qg = lax.all_gather(q[0], axis_name)          # (w, npad) int8 wire
        sg = lax.all_gather(scales[0], axis_name)     # (w, nb) f32 scales
        deq = dequantize_rows(qg, sg, nloc)
        full = deq.reshape(moved_shape)
        return jnp.moveaxis(full, 0, dim).astype(out_dtype)

    @jax.custom_vjp
    def gather(v):
        return jax.shard_map(body, mesh=mesh, in_specs=shard_spec,
                             out_specs=P(), axis_names={axis_name},
                             check_vma=False)(v)

    def fwd(v):
        return gather(v), None

    def bwd(_, g):
        # straight-through: the constraint places the cotangent on the
        # ZeRO shard, so the gradient wire is one reduce-scatter per leaf
        return (constrain(g.astype(in_dtype), shard_spec),)

    gather.defvjp(fwd, bwd)
    return gather(x)


def quantized_all_reduce(x, axis_name, *, bits=1, block_size=None,
                         intra_size=0, worker_error=None,
                         server_error=None):
    """EQuARX-style quantized MEAN-all-reduce of per-device ``x`` over
    ``axis_name`` (arxiv 2506.17615): quantize -> reduce-scatter ->
    requantize -> all-gather, entirely in the quantized wire format.

    Must run inside shard_map with ``axis_name`` manual — the same
    GSPMD gotcha as :func:`quantized_all_gather`: only manual-mode
    collectives pin the sub-byte payload dtype in the compiled HLO.
    ``x`` is the device-local flat fp32 tensor; ``x.size`` must divide
    the axis size (1-bit rows pad to the 8-sign byte quantum
    internally, per ``quantization.sign_pack_layout``).

    ``bits`` selects the wire: 8 = blockwise int8 (the qgZ code), 1 =
    packed sign bits + per-block mean-magnitude fp32 scales (the 0/1
    Adam code, arxiv 2202.06009).  Error feedback: ``worker_error``
    (x-shaped) is added before the first quantize, ``server_error``
    (chunk-shaped, ``x.size // w``) at the reduced mean before the
    requantize; both residuals are returned updated.  The hierarchical
    hop-2 requantize and the all-gather hops are stateless — their
    quantization error is not compensated (int8 keeps it negligible;
    the 1-bit engine path absorbs it in the next round's residual).

    Hierarchical scheme (1 < intra_size < w, intra_size | w): the qgZ
    two-hop ``axis_index_groups`` composition on both phases — the
    reduce-scatter runs intra-group then inter-group on requantized
    partial sums, the all-gather runs inter-group then intra-group on
    the same quantized payload (no re-encode: gathers move code, not
    values), so cross-group traffic drops to 1/intra_size.

    Overflow safety: non-finite inputs give non-finite block scales in
    both formats, so the averaged output comes back non-finite and the
    fp16 loss-scale overflow check still trips through the wire.

    Returns ``(mean, new_worker_error, new_server_error)``.
    """
    from deepspeed_tpu.runtime.quantization import (DEFAULT_BLOCK_SIZE,
                                                    dequantize_rows,
                                                    dequantize_signs_rows,
                                                    quantize_rows,
                                                    quantize_signs_rows)

    if block_size is None:
        block_size = DEFAULT_BLOCK_SIZE
    assert bits in (1, 8), f"quantized_all_reduce: bits must be 1 or 8, got {bits}"
    if bits == 1:
        def quant(rows):
            return quantize_signs_rows(rows, block_size)

        def dequant(q, s, n):
            return dequantize_signs_rows(q, s, n, block_size=block_size)
    else:
        def quant(rows):
            return quantize_rows(rows, block_size)

        def dequant(q, s, n):
            return dequantize_rows(q, s, n)

    w = lax.axis_size(axis_name)
    n = x.size
    xf = x.astype(jnp.float32).reshape(-1)
    we = jnp.zeros_like(xf) if worker_error is None else \
        worker_error.astype(jnp.float32).reshape(-1)
    buf = xf + we

    if w == 1:
        # single-device twin: both quantization stages run locally so the
        # numerics (and residual state) match the distributed scheme
        se = jnp.zeros_like(xf) if server_error is None else \
            server_error.astype(jnp.float32).reshape(-1)
        return quantized_error_feedback(xf, we, se, bits=bits,
                                        block_size=block_size)

    assert n % w == 0, \
        f"quantized_all_reduce needs size % {w} == 0, got {n}"
    nloc = n // w
    rows = buf.reshape(w, nloc)

    k = int(intra_size or 0)
    if not (1 < k < w and w % k == 0):
        k = 0

    # --- reduce-scatter phase: after it rank r holds sum chunk r ---------
    if not k:
        q, s = quant(rows)
        new_we = buf - dequant(q, s, nloc).reshape(-1)
        qr = lax.all_to_all(q, axis_name, 0, 0, tiled=False)
        sr = lax.all_to_all(s, axis_name, 0, 0, tiled=False)
        total = dequant(qr, sr, nloc).sum(0)
    else:
        m_g = w // k
        groups_intra = [[o * k + i for i in range(k)] for o in range(m_g)]
        groups_inter = [[o * k + i for o in range(m_g)] for i in range(k)]
        # hop 1 (intra): regroup rows so the k pieces sent within my group
        # are keyed by destination INTRA index (quantized_reduce_scatter)
        x1 = rows.reshape(m_g, k, nloc).transpose(1, 0, 2).reshape(k, -1)
        q1, s1 = quant(x1)
        new_we = (x1 - dequant(q1, s1, m_g * nloc)).reshape(
            k, m_g, nloc).transpose(1, 0, 2).reshape(-1)
        qr1 = lax.all_to_all(q1, axis_name, 0, 0, tiled=False,
                             axis_index_groups=groups_intra)
        sr1 = lax.all_to_all(s1, axis_name, 0, 0, tiled=False,
                             axis_index_groups=groups_intra)
        partial = dequant(qr1, sr1, m_g * nloc).sum(0)
        # hop 2 (inter): requantized partial sums, 1/k the flat traffic
        q2, s2 = quant(partial.reshape(m_g, nloc))
        qr2 = lax.all_to_all(q2, axis_name, 0, 0, tiled=False,
                             axis_index_groups=groups_inter)
        sr2 = lax.all_to_all(s2, axis_name, 0, 0, tiled=False,
                             axis_index_groups=groups_inter)
        total = dequant(qr2, sr2, nloc).sum(0)

    # --- requantize phase: server residual at the mean -------------------
    se = jnp.zeros((nloc,), jnp.float32) if server_error is None else \
        server_error.astype(jnp.float32).reshape(-1)
    mean = total / w + se
    qm, sm = quant(mean.reshape(1, -1))
    new_se = mean - dequant(qm, sm, nloc)[0]

    # --- all-gather phase: broadcast every rank's quantized mean chunk ---
    if not k:
        qg = lax.all_gather(qm[0], axis_name)
        sg = lax.all_gather(sm[0], axis_name)
        out = dequant(qg, sg, nloc).reshape(-1)
    else:
        # hop A (inter): my inter group holds chunks {o*k + i, all o};
        # hop B (intra): group members contribute their hop-A buffers.
        # The payload stays in code form across both hops — gathers move
        # the quantized bytes, values decode once at the end.
        qa = lax.all_gather(qm[0], axis_name, axis_index_groups=groups_inter)
        sa = lax.all_gather(sm[0], axis_name, axis_index_groups=groups_inter)
        qb = lax.all_gather(qa, axis_name, axis_index_groups=groups_intra)
        sb = lax.all_gather(sa, axis_name, axis_index_groups=groups_intra)
        deq = dequant(qb.reshape(k * m_g, -1), sb.reshape(k * m_g, -1),
                      nloc)
        # qb is indexed [intra][outer]; global chunk c = outer*k + intra
        out = deq.reshape(k, m_g, nloc).transpose(1, 0, 2).reshape(-1)
    return out, new_we, new_se


def quantized_all_reduce_gspmd(x, mesh, *, axis_name="data", bits=1,
                               block_size=None, intra_size=0,
                               worker_error=None, server_error=None):
    """GSPMD entry for :func:`quantized_all_reduce`: callable from a
    plain jit under ``mesh`` instead of inside shard_map.

    ``x`` is the stacked per-device contribution of shape ``(w, n)``
    with the leading dim sharded over ``axis_name`` (the engine's
    residual-leaf layout); ``worker_error`` matches ``x`` and
    ``server_error`` is ``(w, n // w)``.  The quantize -> exchange ->
    dequantize core runs inside a leaf-level ``shard_map`` so the
    compiled wire is the packed sub-byte payload (see
    quantized_all_gather's docstring for why a sharding-constraint
    formulation silently fattens back to fp32).

    Returns ``(mean (n,) replicated, new_worker_error, new_server_error)``.
    Differentiable in ``x`` with a straight-through vjp: the quantizer
    passes the cotangent through unchanged, so ``d mean / d x_r = g/w``
    broadcast back onto the per-device layout; residual outputs are
    non-differentiable (their cotangents are dropped).
    """
    from jax.sharding import PartitionSpec as P

    from deepspeed_tpu.parallel.mesh import constrain

    w = int(mesh.shape[axis_name])
    assert x.ndim == 2 and x.shape[0] == w, \
        f"quantized_all_reduce_gspmd wants ({w}, n) stacked input, " \
        f"got {x.shape}"
    n = x.shape[1]
    we = jnp.zeros_like(x) if worker_error is None else worker_error
    se = jnp.zeros((w, max(1, n // max(w, 1))), jnp.float32) \
        if server_error is None else server_error
    row = P(axis_name, None)

    def body(xs, wes, ses):
        out, nwe, nse = quantized_all_reduce(
            xs[0], axis_name, bits=bits, block_size=block_size,
            intra_size=intra_size, worker_error=wes[0],
            server_error=ses[0])
        return out, nwe[None], nse[None]

    def mapped(v, wes, ses):
        return jax.shard_map(
            body, mesh=mesh, in_specs=(row, row, row),
            out_specs=(P(), row, row), axis_names={axis_name},
            check_vma=False)(v, wes, ses)

    @jax.custom_vjp
    def ar(v):
        return mapped(v, we, se)

    def fwd(v):
        return ar(v), None

    def bwd(_, cts):
        g_mean = cts[0]
        return (constrain(
            jnp.broadcast_to(g_mean[None, :] / w, (w, n)).astype(x.dtype),
            row),)

    ar.defvjp(fwd, bwd)
    return ar(x)


def quantized_error_feedback(x, worker_error, server_error, *, bits=1,
                             block_size=None):
    """Single-device twin of :func:`quantized_all_reduce` (w == 1): both
    quantization stages run locally with persistent residuals, matching the
    distributed numerics when every worker holds identical input (the
    engine's already-mesh-averaged SPMD flow) — the blockwise analog of
    :func:`quantize_with_error_feedback`.

    Returns ``(out, new_worker_error, new_server_error)``; all three are
    flat and ``x``-sized.
    """
    from deepspeed_tpu.runtime.quantization import (DEFAULT_BLOCK_SIZE,
                                                    dequantize_rows,
                                                    dequantize_signs_rows,
                                                    quantize_rows,
                                                    quantize_signs_rows)

    if block_size is None:
        block_size = DEFAULT_BLOCK_SIZE
    assert bits in (1, 8)
    if bits == 1:
        def quant(rows):
            return quantize_signs_rows(rows, block_size)

        def dequant(q, s, n):
            return dequantize_signs_rows(q, s, n, block_size=block_size)
    else:
        def quant(rows):
            return quantize_rows(rows, block_size)

        def dequant(q, s, n):
            return dequantize_rows(q, s, n)

    n = x.size
    buf = x.astype(jnp.float32).reshape(-1) + worker_error.reshape(-1)
    q, s = quant(buf.reshape(1, -1))
    stage1 = dequant(q, s, n)[0]
    new_we = buf - stage1
    m = stage1 + server_error.reshape(-1)
    q2, s2 = quant(m.reshape(1, -1))
    out = dequant(q2, s2, n)[0]
    return out, new_we, m - out


def quantize_with_error_feedback(x, worker_error, server_error):
    """Single-device equivalent of compressed_allreduce (w == 1): two
    sequential sign-compressions with persistent residuals.

    Used by OnebitAdam when gradients are already mesh-averaged (the engine's
    SPMD flow): the quantization numerics — including both error-feedback
    stages — match the distributed scheme with identical per-worker input.
    """
    buf = x + worker_error
    worker_scale, signs, new_worker_error = _sign_compress(buf)
    server_m = worker_scale * signs + server_error
    server_scale, server_signs, new_server_error = _sign_compress(server_m)
    return server_scale * server_signs, new_worker_error, new_server_error
