"""Error-compensated compressed collectives — TPU-native 1-bit allreduce.

Reference behavior (deepspeed/runtime/fp16/onebit_adam.py:104-228 +
runtime/custom_collectives.py:10-152): each worker adds its error-feedback
residual, sign-compresses (scale = ||x||_2/sqrt(n), sign with 0 -> +1),
scatters chunk j to "server" j; each server averages the w compressed chunks,
re-compresses with its own residual, and all-gathers the result.

Here the same two-phase scheme runs *inside one jitted step* over a named mesh
axis: `lax.all_to_all` is the worker->server scatter-gather, `lax.all_gather`
broadcasts the server result, and signs travel bit-packed in uint8 (32x less
traffic than fp32 — the same wire format the reference gets from
cupy.packbits). mpi4py/cupy stream juggling disappears; XLA schedules the
collectives on ICI/DCN.
"""
import jax
import jax.numpy as jnp
from jax import lax

_POW2 = jnp.asarray([128, 64, 32, 16, 8, 4, 2, 1], dtype=jnp.uint8)


def pack_signs(signs):
    """{-1,+1} float vector (len % 8 == 0) -> uint8 bit-packed vector."""
    bits = (signs > 0).astype(jnp.uint8).reshape(-1, 8)
    return (bits * _POW2[None, :]).sum(-1).astype(jnp.uint8)


def unpack_signs(packed):
    """uint8 bit-packed vector -> {-1,+1} float32 vector."""
    bits = (packed[:, None] // _POW2[None, :]) % 2
    return (bits.astype(jnp.float32) * 2.0 - 1.0).reshape(-1)


def _sign_compress(x):
    """Returns (scale, signs, residual): x ~= scale*signs, residual = x - that.

    scale = ||x||_2 / sqrt(n) (reference onebit_adam.py:123); sign(0) -> +1
    (the reference's sign().add_(1).bool() mapping, onebit_adam.py:124-127).
    """
    scale = jnp.linalg.norm(x) / jnp.sqrt(jnp.float32(x.size))
    signs = jnp.where(x >= 0, 1.0, -1.0).astype(jnp.float32)
    return scale, signs, x - scale * signs


def compressed_allreduce(x, worker_error, server_error, axis_name):
    """Error-compensated 1-bit average of per-device `x` over `axis_name`.

    Must be called inside shard_map/pmap with `axis_name` bound. `x` is the
    device-local flat fp32 tensor, length divisible by 8*axis_size; ``x.size
    == worker_error.size``; ``server_error`` is either chunk-sized
    (x.size // axis_size, this device's server residual) or full-sized
    (x.size — this device's chunk is sliced at axis_index and written back,
    so optimizer state stays param-shaped).

    Returns (averaged_x, new_worker_error, new_server_error).
    """
    w = lax.axis_size(axis_name)
    n = x.size
    assert n % (8 * w) == 0, f"compressed_allreduce needs size % {8*w} == 0, got {n}"
    full_server_error = server_error.size == n
    if full_server_error:
        idx = lax.axis_index(axis_name)
        server_error_full = server_error
        server_error = lax.dynamic_slice(server_error, (idx * (n // w),),
                                         (n // w,))

    # --- worker phase: compensate, compress, scatter chunks to servers ----
    buf = x + worker_error
    worker_scale, signs, new_worker_error = _sign_compress(buf)
    packed = pack_signs(signs).reshape(w, n // (8 * w))
    # chunk j of every worker lands on device j: rows = per-worker signs of my chunk
    recv = lax.all_to_all(packed, axis_name, split_axis=0, concat_axis=0,
                          tiled=False)
    scales = lax.all_gather(worker_scale, axis_name)             # (w,)
    if recv.ndim == 1:  # w == 1 keeps the row dim collapsed
        recv = recv.reshape(w, -1)
    worker_signs = unpack_signs(recv.reshape(-1)).reshape(w, n // w)

    # --- server phase: average, re-compress with server residual ---------
    server_m = (worker_signs * scales[:, None]).sum(0) / w + server_error
    server_scale, server_signs, new_server_error = _sign_compress(server_m)
    server_packed = pack_signs(server_signs)

    # --- broadcast: all-gather every server's compressed chunk -----------
    all_packed = lax.all_gather(server_packed, axis_name)        # (w, n/8w)
    all_scales = lax.all_gather(server_scale, axis_name)         # (w,)
    out_signs = unpack_signs(all_packed.reshape(-1)).reshape(w, n // w)
    out = (out_signs * all_scales[:, None]).reshape(-1)
    if full_server_error:
        new_server_error = lax.dynamic_update_slice(
            server_error_full, new_server_error, (idx * (n // w),))
    return out, new_worker_error, new_server_error


def quantize_with_error_feedback(x, worker_error, server_error):
    """Single-device equivalent of compressed_allreduce (w == 1): two
    sequential sign-compressions with persistent residuals.

    Used by OnebitAdam when gradients are already mesh-averaged (the engine's
    SPMD flow): the quantization numerics — including both error-feedback
    stages — match the distributed scheme with identical per-worker input.
    """
    buf = x + worker_error
    worker_scale, signs, new_worker_error = _sign_compress(buf)
    server_m = worker_scale * signs + server_error
    server_scale, server_signs, new_server_error = _sign_compress(server_m)
    return server_scale * server_signs, new_worker_error, new_server_error
