"""Static and dynamic loss scaling.

Reference semantics: deepspeed/runtime/fp16/loss_scaler.py:56-221 —
``DynamicLossScaler`` doubles every ``scale_window`` overflow-free iterations,
halves on overflow, with ``delayed_shift`` hysteresis and a ``min_scale`` floor.

TPU-native form: the scaler is a small pytree (`LossScaleState`) updated inside
the jitted train step with ``lax.cond`` — the data-dependent skip/halve logic
stays on-device, no host sync (SURVEY §7 "hard parts").  The host-facing
``LossScaler`` / ``DynamicLossScaler`` classes keep the reference API for
config plumbing and tests.
"""
from typing import NamedTuple

INITIAL_LOSS_SCALE = "init_scale"
SCALE_WINDOW = "scale_window"
DELAYED_SHIFT = "delayed_shift"
MIN_LOSS_SCALE = "min_scale"


class LossScaleState(NamedTuple):
    """Device-side scaler state (all scalars)."""
    loss_scale: object          # f32
    cur_iter: object            # i32
    last_overflow_iter: object  # i32
    cur_hysteresis: object      # i32


def make_loss_scale_state(init_scale, delayed_shift=1):
    import jax.numpy as jnp

    return LossScaleState(
        loss_scale=jnp.float32(init_scale),
        cur_iter=jnp.int32(0),
        last_overflow_iter=jnp.int32(-1),
        cur_hysteresis=jnp.int32(delayed_shift))


def update_loss_scale(state: LossScaleState, overflow, *, scale_factor=2.0,
                      scale_window=1000, min_scale=1.0, delayed_shift=1,
                      consecutive_hysteresis=False, dynamic=True):
    """Pure update implementing the reference update_scale (loss_scaler.py:151)."""
    import jax.numpy as jnp

    if not dynamic:
        return LossScaleState(state.loss_scale, state.cur_iter + 1,
                              state.last_overflow_iter, state.cur_hysteresis)

    def on_overflow(s):
        shift_now = s.cur_hysteresis <= 1
        new_scale = jnp.where(shift_now,
                              jnp.maximum(s.loss_scale / scale_factor,
                                          jnp.float32(min_scale)),
                              s.loss_scale)
        new_hyst = jnp.where(shift_now, s.cur_hysteresis, s.cur_hysteresis - 1)
        return LossScaleState(new_scale, s.cur_iter + 1, s.cur_iter, new_hyst)

    def on_good(s):
        window_hit = jnp.logical_and(
            scale_window > 0,
            (s.cur_iter - s.last_overflow_iter) % scale_window == 0)
        new_scale = jnp.where(window_hit, s.loss_scale * scale_factor, s.loss_scale)
        if consecutive_hysteresis:
            new_hyst = jnp.int32(delayed_shift)
        else:
            new_hyst = jnp.where(window_hit, jnp.int32(delayed_shift), s.cur_hysteresis)
        return LossScaleState(new_scale, s.cur_iter + 1, s.last_overflow_iter, new_hyst)

    import jax

    return jax.lax.cond(overflow, on_overflow, on_good, state)


# ---------------------------------------------------------------------------
# Host-facing classes (API parity with reference loss_scaler.py)
# ---------------------------------------------------------------------------

class LossScalerBase:
    def __init__(self, cur_scale):
        self.cur_scale = cur_scale

    @property
    def loss_scale(self):
        return self.cur_scale

    def scale_gradient(self, grads):
        import jax

        return jax.tree_util.tree_map(lambda g: g * self.loss_scale, grads)

    def update_scale(self, overflow):
        pass

    def backward(self, loss):
        return loss * self.loss_scale


class LossScaler(LossScalerBase):
    """Static loss scale (reference :56-77): never reports overflow."""

    def __init__(self, scale=1):
        super().__init__(scale)

    def has_overflow(self, params):
        return False


class DynamicLossScaler(LossScalerBase):
    """Dynamic scaler (reference :79-221)."""

    def __init__(self, init_scale=2 ** 32, scale_factor=2., scale_window=1000,
                 min_scale=1, delayed_shift=1, consecutive_hysteresis=False):
        super().__init__(init_scale)
        self.cur_iter = 0
        self.last_overflow_iter = -1
        self.scale_factor = scale_factor
        self.scale_window = scale_window
        self.min_scale = min_scale
        self.delayed_shift = delayed_shift
        self.cur_hysteresis = delayed_shift
        self.consecutive_hysteresis = consecutive_hysteresis

    def update_scale(self, overflow):
        if overflow:
            if self.delayed_shift == 1 or self.cur_hysteresis == 1:
                self.cur_scale = max(self.cur_scale / self.scale_factor, self.min_scale)
            else:
                self.cur_hysteresis -= 1
            self.last_overflow_iter = self.cur_iter
        else:
            if self.consecutive_hysteresis:
                self.cur_hysteresis = self.delayed_shift
            if (self.cur_iter - self.last_overflow_iter) % self.scale_window == 0:
                if not self.consecutive_hysteresis:
                    self.cur_hysteresis = self.delayed_shift
                self.cur_scale *= self.scale_factor
        self.cur_iter += 1


def CreateLossScaler(static_loss_scale=0, dynamic_scale_args=None):
    """Factory matching the engine's config semantics: loss_scale==0 => dynamic."""
    if static_loss_scale and static_loss_scale > 0:
        return LossScaler(scale=static_loss_scale)
    if dynamic_scale_args:
        return DynamicLossScaler(
            init_scale=dynamic_scale_args.get(INITIAL_LOSS_SCALE, 2 ** 32),
            scale_window=dynamic_scale_args.get(SCALE_WINDOW, 1000),
            delayed_shift=dynamic_scale_args.get(DELAYED_SHIFT, 1),
            min_scale=dynamic_scale_args.get(MIN_LOSS_SCALE, 1))
    return DynamicLossScaler()
