"""Data loading: host batches -> mesh-sharded device arrays.

Reference: deepspeed/runtime/dataloader.py (DeepSpeedDataLoader with
DistributedSampler auto-wiring :33, RepeatingLoader :10).  TPU-native: the
loader yields numpy/dict batches; the engine places them on the mesh with the
batch dim sharded over 'data' (jax.make_array_from_process_local_data under
multi-host).  Works with torch DataLoaders, HF datasets, or any iterable.
"""
import numpy as np

from deepspeed_tpu.utils.logging import logger


class RepeatingLoader:
    """Wrap an iterator to restart automatically when exhausted
    (reference dataloader.py:10-30; used by the pipeline engine)."""

    def __init__(self, loader):
        self.loader = loader
        self.data_iter = iter(self.loader)

    def __iter__(self):
        return self

    def __next__(self):
        try:
            batch = next(self.data_iter)
        except StopIteration:
            self.data_iter = iter(self.loader)
            batch = next(self.data_iter)
        return batch


class DeepSpeedDataLoader:
    """Iterates a dataset in micro-batches for this process.

    If ``dataset`` is a torch Dataset, a DataLoader with a distributed sampler
    over data-parallel ranks is built (reference behavior); any other iterable
    is consumed as-is.  len() = number of micro-batches per epoch.
    """

    def __init__(self, dataset, batch_size, pin_memory=False, local_rank=0,
                 tput_timer=None, collate_fn=None, num_local_io_workers=0,
                 data_sampler=None, data_parallel_world_size=1,
                 data_parallel_rank=0):
        self.batch_size = batch_size
        self.tput_timer = tput_timer
        self._torch_loader = None
        self._iterable = None

        try:
            import torch.utils.data as tud

            is_torch_dataset = isinstance(dataset, tud.Dataset)
        except Exception:
            tud = None
            is_torch_dataset = False

        if is_torch_dataset:
            if data_sampler is None:
                if data_parallel_world_size > 1:
                    data_sampler = tud.distributed.DistributedSampler(
                        dataset, num_replicas=data_parallel_world_size,
                        rank=data_parallel_rank)
                else:
                    data_sampler = tud.RandomSampler(dataset)
            self._torch_loader = tud.DataLoader(
                dataset, batch_size=batch_size, sampler=data_sampler,
                collate_fn=collate_fn, num_workers=num_local_io_workers,
                pin_memory=pin_memory)
            self.len = len(self._torch_loader)
        else:
            self._iterable = dataset
            try:
                self.len = len(dataset)
            except TypeError:
                self.len = 0

    def __len__(self):
        return self.len

    def __iter__(self):
        if self.tput_timer:
            self.tput_timer.start()
        src = self._torch_loader if self._torch_loader is not None else self._iterable
        for batch in src:
            yield to_numpy_batch(batch)


def to_numpy_batch(batch):
    """Convert torch tensors / lists to numpy, preserving dict/tuple structure."""
    if isinstance(batch, dict):
        return {k: to_numpy_batch(v) for k, v in batch.items()}
    if isinstance(batch, (list, tuple)):
        return type(batch)(to_numpy_batch(v) for v in batch)
    if hasattr(batch, "detach"):  # torch tensor
        return batch.detach().cpu().numpy()
    return np.asarray(batch)
