"""Blockwise int8 quantization for the ZeRO collectives (qwZ / qgZ).

ZeRO++ (arxiv 2306.10209) cuts ZeRO communication ~4x by moving the weight
all-gather (qwZ) and the gradient reduce-scatter (qgZ) as block-quantized
int8 + per-block fp32 scales instead of fp16/fp32; EQuARX (arxiv 2506.17615)
shows the same scheme lands natively inside XLA collectives.  This module is
the shared quantize/dequantize layer: pure jnp (usable inside shard_map and
jit) plus numpy twins for the host side of the ZeRO-Offload push path.

Scheme: symmetric per-block scales.  For each block b of ``block_size``
contiguous elements: ``scale_b = max|x_b| / 127``, ``q = clip(round(x /
scale_b), -127, 127)`` stored as int8.  Wire overhead is one fp32 scale per
block (4/block_size bytes/element), so fp32 -> int8+scales is a
``4 / (1 + 4/block_size)`` byte reduction (3.88x at the default block 128).

Overflow safety: a block containing inf/nan gets a non-finite scale (the
abs-max propagates), so dequantized values come back non-finite and the
engine's loss-scale overflow check still fires — quantization cannot mask a
gradient overflow.

Error feedback is optional (`quantize_blockwise_ef`): callers that persist a
residual across steps (the 1-bit machinery in custom_collectives.py does the
sign-compression analog) add it before quantizing and carry the new residual
forward; the stateless functions are exact enough for int8 that the engine's
qgZ path runs without residual state by default.
"""
import numpy as np

import jax.numpy as jnp

DEFAULT_BLOCK_SIZE = 128
_QMAX = 127.0


def block_layout(n: int, block_size: int = DEFAULT_BLOCK_SIZE):
    """(effective_block, n_blocks, padded_n) for a row of ``n`` elements.

    The effective block is clamped to the row length so small rows don't pay
    a full block of zero padding (a (16,16) leaf sharded 8 ways yields
    32-element rows; padding those to 128 would cost more wire than fp32).
    Shared by the quantizers AND the analytic comm accounting — the two must
    agree for the accounting to be byte-accurate.
    """
    assert n > 0, "cannot lay out an empty row"
    bs = max(1, min(int(block_size), n))
    nb = -(-n // bs)
    return bs, nb, nb * bs


def quantize_rows(x, block_size: int = DEFAULT_BLOCK_SIZE):
    """Quantize each row of ``x`` (r, n) independently.

    Returns ``(q, scales)``: ``q`` int8 of shape (r, npad) (rows padded with
    zeros to a block multiple), ``scales`` fp32 of shape (r, nb).
    """
    r, n = x.shape
    bs, nb, npad = block_layout(n, block_size)
    xf = x.astype(jnp.float32)
    if npad != n:
        xf = jnp.pad(xf, ((0, 0), (0, npad - n)))
    blocks = xf.reshape(r, nb, bs)
    amax = jnp.max(jnp.abs(blocks), axis=-1)          # inf/nan propagate
    scales = amax / _QMAX
    safe = jnp.where(scales > 0, scales, 1.0)
    q = jnp.clip(jnp.round(blocks / safe[:, :, None]), -_QMAX, _QMAX)
    return q.astype(jnp.int8).reshape(r, npad), scales


def dequantize_rows(q, scales, n: int, dtype=jnp.float32):
    """Inverse of quantize_rows: (r, npad) int8 + (r, nb) -> (r, n)."""
    r, npad = q.shape
    nb = scales.shape[1]
    blocks = q.reshape(r, nb, npad // nb).astype(jnp.float32)
    out = (blocks * scales[:, :, None]).reshape(r, npad)
    return out[:, :n].astype(dtype)


def quantize_blockwise(x, block_size: int = DEFAULT_BLOCK_SIZE):
    """Flatten-and-quantize a whole array: returns (q[npad] int8, scales[nb])."""
    q, scales = quantize_rows(x.reshape(1, -1), block_size)
    return q[0], scales[0]


def dequantize_blockwise(q, scales, shape, dtype=jnp.float32):
    """Inverse of quantize_blockwise back to ``shape``."""
    n = int(np.prod(shape))
    return dequantize_rows(q[None], scales[None], n, dtype)[0].reshape(shape)


def quantize_blockwise_ef(x, residual, block_size: int = DEFAULT_BLOCK_SIZE):
    """Error-feedback variant: quantize ``x + residual`` and return
    ``(q, scales, new_residual)`` where the new residual is the quantization
    error to add back next round (the compensation scheme of
    custom_collectives._sign_compress, at int8 precision)."""
    comp = x.astype(jnp.float32) + residual
    q, scales = quantize_blockwise(comp, block_size)
    deq = dequantize_blockwise(q, scales, comp.shape)
    return q, scales, comp - deq


# ---------------------------------------------------------------------------
# 1-bit sign quantization — the 0/1 Adam wire (arxiv 2202.06009) one rung
# below qgZ: one SIGN BIT per element plus one fp32 scale per block, packed
# 8 signs/byte.  ``scale_b = mean|x_b|`` (the L1-optimal magnitude for a
# sign code; inf/nan propagate through the mean so overflow still trips the
# loss scaler).  Dequantized value is ``sign * scale_b`` — padding tail
# elements decode to +scale and MUST be sliced off by the caller; the
# error-feedback residual absorbs the per-block magnitude loss.
# ---------------------------------------------------------------------------

_POW2 = jnp.asarray([128, 64, 32, 16, 8, 4, 2, 1], dtype=jnp.uint8)


def sign_pack_layout(n: int, block_size: int = DEFAULT_BLOCK_SIZE):
    """(effective_block, n_blocks, padded_n, packed_bytes) for a row of
    ``n`` elements under the 1-bit wire.  Extends ``block_layout`` with the
    byte-packing quantum: signs pack 8/byte, so the padded row is rounded up
    again to a multiple of 8 and ``packed_bytes = ceil(padded_n / 8)``.
    Shared by the quantizers AND the analytic comm accounting — the two must
    agree for the 1-bit accounting to be byte-accurate."""
    bs, nb, npad = block_layout(n, block_size)
    npack = -(-npad // 8) * 8
    return bs, nb, npad, npack // 8


def quantize_signs_rows(x, block_size: int = DEFAULT_BLOCK_SIZE):
    """1-bit quantize each row of ``x`` (r, n) independently.

    Returns ``(packed, scales)``: ``packed`` uint8 of shape (r, packed_bytes)
    with 8 MSB-first sign bits per byte (bit set = non-negative), ``scales``
    fp32 of shape (r, nb) holding per-block mean magnitudes.
    """
    r, n = x.shape
    bs, nb, npad, nbytes = sign_pack_layout(n, block_size)
    xf = x.astype(jnp.float32)
    if npad != n:
        xf = jnp.pad(xf, ((0, 0), (0, npad - n)))
    scales = jnp.mean(jnp.abs(xf.reshape(r, nb, bs)), axis=-1)
    bits = (xf >= 0).astype(jnp.uint8)                # nan -> sign bit 0
    if nbytes * 8 != npad:
        bits = jnp.pad(bits, ((0, 0), (0, nbytes * 8 - npad)))
    packed = (bits.reshape(r, nbytes, 8) * _POW2).sum(
        axis=-1, dtype=jnp.uint8)
    return packed, scales


def dequantize_signs_rows(packed, scales, n: int, dtype=jnp.float32,
                          block_size: int = DEFAULT_BLOCK_SIZE):
    """Inverse of quantize_signs_rows: (r, packed_bytes) uint8 + (r, nb)
    scales -> (r, n) with each element ``±scale_of_its_block``."""
    r = packed.shape[0]
    bs, nb, npad, nbytes = sign_pack_layout(n, block_size)
    bits = (packed[:, :, None] & _POW2[None, None, :]) > 0
    signs = bits.reshape(r, nbytes * 8)[:, :npad].astype(
        jnp.float32) * 2.0 - 1.0
    out = signs.reshape(r, nb, bs) * scales[:, :, None]
    return out.reshape(r, npad)[:, :n].astype(dtype)


def quantize_signs_rows_np(x, block_size: int = DEFAULT_BLOCK_SIZE):
    """numpy twin of quantize_signs_rows (bit-identical packing layout)."""
    x = np.asarray(x, dtype=np.float32)
    r, n = x.shape
    bs, nb, npad, nbytes = sign_pack_layout(n, block_size)
    if npad != n:
        x = np.pad(x, ((0, 0), (0, npad - n)))
    with np.errstate(invalid="ignore"):
        scales = np.mean(np.abs(x.reshape(r, nb, bs)), axis=-1)
    bits = (x >= 0).astype(np.uint8)
    if nbytes * 8 != npad:
        bits = np.pad(bits, ((0, 0), (0, nbytes * 8 - npad)))
    pow2 = np.array([128, 64, 32, 16, 8, 4, 2, 1], dtype=np.uint8)
    packed = (bits.reshape(r, nbytes, 8) * pow2).sum(-1).astype(np.uint8)
    return packed, scales.astype(np.float32)


def dequantize_signs_rows_np(packed, scales, n: int, dtype=np.float32,
                             block_size: int = DEFAULT_BLOCK_SIZE):
    r = packed.shape[0]
    bs, nb, npad, nbytes = sign_pack_layout(n, block_size)
    pow2 = np.array([128, 64, 32, 16, 8, 4, 2, 1], dtype=np.uint8)
    bits = (packed[:, :, None] & pow2[None, None, :]) > 0
    signs = bits.reshape(r, nbytes * 8)[:, :npad].astype(
        np.float32) * 2.0 - 1.0
    # invalid-multiply is expected: non-finite scales deliberately poison
    # their block (overflow propagation, see module docstring)
    with np.errstate(invalid="ignore"):
        out = signs.reshape(r, nb, bs) * scales[:, :, None]
    return out.reshape(r, npad)[:, :n].astype(dtype)


# ---------------------------------------------------------------------------
# numpy twins — host side of the ZeRO-Offload qwZ push (quantize on the host,
# upload int8, dequantize after the on-device all-gather)
# ---------------------------------------------------------------------------

def quantize_blockwise_np(x, block_size: int = DEFAULT_BLOCK_SIZE):
    """numpy quantize of a flat array: (q[npad] int8, scales[nb] f32)."""
    x = np.asarray(x, dtype=np.float32).reshape(-1)
    bs, nb, npad = block_layout(x.size, block_size)
    if npad != x.size:
        x = np.pad(x, (0, npad - x.size))
    blocks = x.reshape(nb, bs)
    with np.errstate(invalid="ignore"):
        amax = np.max(np.abs(blocks), axis=-1)
    scales = amax / _QMAX
    safe = np.where(scales > 0, scales, 1.0)
    with np.errstate(invalid="ignore"):
        q = np.clip(np.round(blocks / safe[:, None]), -_QMAX, _QMAX)
    # nan -> 0 explicitly: np.int8(nan) is platform-defined, and the scale
    # already carries the non-finite marker to the dequantized side
    q = np.where(np.isfinite(q), q, 0.0)
    return q.astype(np.int8).reshape(npad), scales.astype(np.float32)


def dequantize_blockwise_np(q, scales, n: int, dtype=np.float32):
    nb = scales.shape[0]
    blocks = q.reshape(nb, q.size // nb).astype(np.float32)
    # invalid-multiply is expected: non-finite scales deliberately poison
    # their block (overflow propagation, see module docstring)
    with np.errstate(invalid="ignore"):
        return (blocks * scales[:, None]).reshape(-1)[:n].astype(dtype)
