from deepspeed_tpu.profiling.flops_profiler.profiler import (
    FlopsProfiler, analyze_jit, duration_to_string, flops_to_string,
    get_model_profile, macs_to_string, number_to_string, params_to_string)
