"""Flops profiler — XLA cost analysis instead of torch monkey-patching.

Reference behavior: deepspeed/profiling/flops_profiler/profiler.py:33-520
(wraps torch.nn.functional to count flops per module, forward hooks for
latency, per-module tree print, top-k aggregation). On TPU the compiler
already knows the cost: `Compiled.cost_analysis()` reports flops and bytes
for the exact fused program that runs, so the profiler lowers the jitted
function once and reads the analysis — no instrumentation in the hot path.

Per-module breakdown: optional `breakdown(fns)` profiles a dict of
name -> (fn, args) pairs (e.g. one per layer) the same way; utilization is
flops/sec against a supplied or detected peak.
"""
import time
from typing import Any, Callable, Dict, Optional, Tuple

from deepspeed_tpu.utils.logging import logger


def _fmt(value, units=None, precision=2):
    """Human units (reference number_to_string/flops_to_string :556-607)."""
    if value is None:
        return "n/a"
    for suffix, scale in [("T", 1e12), ("G", 1e9), ("M", 1e6), ("K", 1e3)]:
        if units == suffix or (units is None and value >= scale):
            return f"{value / scale:.{precision}f} {suffix}"
    return f"{value:.{precision}f} "


def number_to_string(num, precision=2):
    return _fmt(num, precision=precision)


def flops_to_string(flops, units=None, precision=2):
    return _fmt(flops, units, precision) + "FLOPS"


def params_to_string(params_num, units=None, precision=2):
    return _fmt(params_num, units, precision).rstrip()


def macs_to_string(macs, units=None, precision=2):
    return _fmt(macs, units, precision) + "MACs"


def duration_to_string(duration, units=None, precision=2):
    if duration is None:
        return "n/a"
    if duration > 1:
        return f"{duration:.{precision}f} s"
    if duration > 1e-3:
        return f"{duration * 1e3:.{precision}f} ms"
    return f"{duration * 1e6:.{precision}f} us"


def analyze_jit(fn: Callable, *args, static_argnums=()) -> Dict[str, Any]:
    """Lower+compile fn(*args) and return XLA's cost analysis:
    {'flops': float, 'bytes_accessed': float, ...}. Costs are for the
    optimized (fused) HLO — the program that actually runs.  The memory
    side delegates to runtime/memory_accounting.normalize_memory_analysis
    — THE normalizer for the dict/None/per-backend memory_analysis()
    variants (same treatment mfu.normalize_cost_analysis gives the cost
    side)."""
    import jax

    from deepspeed_tpu.runtime.memory_accounting import \
        normalize_memory_analysis

    lowered = jax.jit(fn, static_argnums=static_argnums).lower(*args)
    compiled = lowered.compile()
    cost = compiled.cost_analysis()
    if isinstance(cost, list):  # some backends return a list per computation
        cost = cost[0] if cost else {}
    cost = dict(cost or {})
    mem = normalize_memory_analysis(compiled)
    if mem["modeled"]:
        cost["output_bytes"] = mem["output_bytes"]
        cost["temp_bytes"] = mem["temp_bytes"]
        cost["argument_bytes"] = mem["argument_bytes"]
        cost["peak_bytes"] = mem["peak_bytes"]
    return cost


class FlopsProfiler:
    """Profile the engine's (or any) jitted step.

    Reference API kept: start_profile/stop_profile/end_profile,
    get_total_flops/params/duration, print_model_profile.
    """

    def __init__(self, model=None, engine=None, peak_flops: Optional[float] = None):
        self.model = model
        self.engine = engine
        self.peak_flops = peak_flops
        self._flops = None
        self._params = None
        self._duration = None
        self._cost = {}
        self._comm = None
        self._started = None

    # --- measurement --------------------------------------------------
    def profile_fn(self, fn, *args, n_timing_runs=3, static_argnums=()):
        """Cost-analyze and (optionally) time fn(*args)."""
        import jax

        self._cost = analyze_jit(fn, *args, static_argnums=static_argnums)
        self._flops = self._cost.get("flops")
        if n_timing_runs:
            jitted = jax.jit(fn, static_argnums=static_argnums)
            out = jitted(*args)
            jax.block_until_ready(out)
            t0 = time.time()
            for _ in range(n_timing_runs):
                out = jitted(*args)
            jax.block_until_ready(out)
            self._duration = (time.time() - t0) / n_timing_runs
        return self._cost

    def profile_params(self, params):
        import jax

        self._params = sum(int(x.size)
                           for x in jax.tree_util.tree_leaves(params))
        return self._params

    def breakdown(self, named_fns: Dict[str, Tuple[Callable, tuple]]):
        """Per-component costs: {name: cost_dict}."""
        return {name: analyze_jit(fn, *args)
                for name, (fn, args) in named_fns.items()}

    def profile_comm(self, report: Optional[Dict[str, Any]]):
        """Attach an analytic comm-volume report (the dict produced by
        DeepSpeedEngine.comm_volume_report / runtime.comm_accounting):
        per-step wire bytes show up in print_model_profile alongside the
        compute numbers."""
        self._comm = report
        return report

    # --- reference-API surface ---------------------------------------
    def start_profile(self, ignore_list=None):
        self._started = time.time()

    def stop_profile(self):
        if self._started is not None:
            self._duration = time.time() - self._started

    def end_profile(self):
        self._started = None

    def reset_profile(self):
        self._flops = self._params = self._duration = None
        self._cost = {}
        self._comm = None

    def get_total_flops(self, as_string=False):
        return flops_to_string(self._flops) if as_string else (self._flops or 0)

    def get_total_params(self, as_string=False):
        return params_to_string(self._params) if as_string \
            else (self._params or 0)

    def get_total_duration(self, as_string=False):
        return duration_to_string(self._duration) if as_string \
            else (self._duration or 0)

    def print_model_profile(self, profile_step=1, module_depth=-1,
                            top_modules=3, detailed=True):
        lines = [
            "-------------------------- DeepSpeed Flops Profiler "
            "--------------------------",
            f"Profile step:                   {profile_step}",
            f"Params:                         "
            f"{params_to_string(self._params) if self._params else 'n/a'}",
            f"Fwd/step FLOPs:                 "
            f"{flops_to_string(self._flops) if self._flops else 'n/a'}",
            f"Step latency:                   "
            f"{duration_to_string(self._duration)}",
        ]
        if self._flops and self._duration:
            achieved = self._flops / self._duration
            lines.append(f"Achieved:                       "
                         f"{flops_to_string(achieved)}")
            if self.peak_flops:
                lines.append(f"Utilization:                    "
                             f"{100 * achieved / self.peak_flops:.1f}% of "
                             f"{flops_to_string(self.peak_flops)} peak")
        for key in ("bytes accessed", "bytes_accessed", "temp_bytes",
                    "output_bytes"):
            if self._cost.get(key):
                lines.append(f"{key:<31} {_fmt(self._cost[key])}B")
        if self._comm:
            lines.append(f"Comm bytes/step (analytic):     "
                         f"{_fmt(self._comm['total_bytes_per_step'])}B")
            lines.append(f"  grad exchange:                "
                         f"{_fmt(self._comm['grad_exchange_bytes_per_step'])}B")
            red = self._comm.get("grad_reduction_vs_fp32")
            if red:
                lines.append(f"  vs fp32 dense exchange:       {red:.2f}x")
            if self._comm.get("inter_bytes_per_step"):
                lines.append(
                    f"  cross-group (inter) bytes:    "
                    f"{_fmt(self._comm['inter_bytes_per_step'])}B")
        lines.append("-" * 78)
        for line in lines:
            logger.info(line)
        return "\n".join(lines)


def get_model_profile(model_fn, args, print_profile=True, detailed=True,
                      warm_up=1, as_string=True):
    """Functional one-shot profile (reference get_model_profile :616-682)."""
    prof = FlopsProfiler()
    prof.profile_fn(model_fn, *args, n_timing_runs=max(1, warm_up))
    flops = prof.get_total_flops(as_string)
    duration = prof.get_total_duration(as_string)
    if print_profile:
        prof.print_model_profile()
    return flops, None, duration
