"""Elasticity config keys (reference: deepspeed/elasticity/constants.py).

Format:
  "elasticity": {
    "enabled": false,
    "max_train_batch_size": 2000,
    "micro_batch_sizes": [2, 4, 6],
    "min_gpus": 1,
    "max_gpus": 10000,
    "min_time": 0,
    "version": 0.1,
    "ignore_non_elastic_batch_info": false,
    "prefer_larger_batch": true
  }
"""

ELASTICITY = "elasticity"

ENABLED = "enabled"
ENABLED_DEFAULT = False

MAX_ACCEPTABLE_BATCH_SIZE = "max_train_batch_size"
MAX_ACCEPTABLE_BATCH_SIZE_DEFAULT = 2000

MICRO_BATCHES = "micro_batch_sizes"
MICRO_BATCHES_DEFAULT = [2, 4, 6]

MIN_GPUS = "min_gpus"
MIN_GPUS_DEFAULT = 1

MAX_GPUS = "max_gpus"
MAX_GPUS_DEFAULT = 10000

MIN_TIME = "min_time"
MIN_TIME_DEFAULT = 0

IGNORE_NON_ELASTIC_BATCH_INFO = "ignore_non_elastic_batch_info"
IGNORE_NON_ELASTIC_BATCH_INFO_DEFAULT = False

PREFER_LARGER_BATCH = "prefer_larger_batch"
PREFER_LARGER_BATCH_DEFAULT = True

VERSION = "version"
VERSION_DEFAULT = 0.1

LATEST_ELASTICITY_VERSION = 0.1
# minimum framework version supporting elasticity (reference analog: 0.3.8)
MINIMUM_DEEPSPEED_VERSION = "0.1.0"
