"""Elasticity config object + errors (reference: deepspeed/elasticity/config.py)."""
import json

from deepspeed_tpu.elasticity.constants import (
    ENABLED, ENABLED_DEFAULT, IGNORE_NON_ELASTIC_BATCH_INFO,
    IGNORE_NON_ELASTIC_BATCH_INFO_DEFAULT, MAX_ACCEPTABLE_BATCH_SIZE,
    MAX_ACCEPTABLE_BATCH_SIZE_DEFAULT, MAX_GPUS, MAX_GPUS_DEFAULT,
    MICRO_BATCHES, MICRO_BATCHES_DEFAULT, MIN_GPUS, MIN_GPUS_DEFAULT,
    MIN_TIME, MIN_TIME_DEFAULT, PREFER_LARGER_BATCH,
    PREFER_LARGER_BATCH_DEFAULT, VERSION, VERSION_DEFAULT)


class ElasticityError(Exception):
    """Base elasticity error."""


class ElasticityConfigError(ElasticityError):
    """Invalid elasticity config."""


class ElasticityIncompatibleWorldSize(ElasticityError):
    """Current world size not in the valid elastic world-size set."""


class ElasticityConfig:
    def __init__(self, param_dict):
        self.enabled = param_dict.get(ENABLED, ENABLED_DEFAULT)
        if self.enabled:
            if MAX_ACCEPTABLE_BATCH_SIZE not in param_dict:
                raise ElasticityConfigError(f"Elasticity config missing {MAX_ACCEPTABLE_BATCH_SIZE}")
            if MICRO_BATCHES not in param_dict:
                raise ElasticityConfigError(f"Elasticity config missing {MICRO_BATCHES}")
        self.max_acceptable_batch_size = param_dict.get(
            MAX_ACCEPTABLE_BATCH_SIZE, MAX_ACCEPTABLE_BATCH_SIZE_DEFAULT)
        self.micro_batches = param_dict.get(MICRO_BATCHES, MICRO_BATCHES_DEFAULT)
        if not isinstance(self.micro_batches, list):
            raise ElasticityConfigError(
                f"{MICRO_BATCHES} must be a list of ints, got {self.micro_batches}")
        if not all(isinstance(m, int) and m > 0 for m in self.micro_batches):
            raise ElasticityConfigError(
                f"{MICRO_BATCHES} values must be positive ints, got {self.micro_batches}")
        self.min_gpus = param_dict.get(MIN_GPUS, MIN_GPUS_DEFAULT)
        self.max_gpus = param_dict.get(MAX_GPUS, MAX_GPUS_DEFAULT)
        if self.min_gpus < 1 or self.max_gpus < 1 or self.max_gpus < self.min_gpus:
            raise ElasticityConfigError(
                f"Invalid gpu range: min_gpus={self.min_gpus} max_gpus={self.max_gpus}")
        self.min_time = param_dict.get(MIN_TIME, MIN_TIME_DEFAULT)
        self.version = param_dict.get(VERSION, VERSION_DEFAULT)
        self.prefer_larger_batch_size = param_dict.get(
            PREFER_LARGER_BATCH, PREFER_LARGER_BATCH_DEFAULT)
        self.ignore_non_elastic_batch_info = param_dict.get(
            IGNORE_NON_ELASTIC_BATCH_INFO, IGNORE_NON_ELASTIC_BATCH_INFO_DEFAULT)

    def repr(self):
        return self.__dict__.copy()

    def __repr__(self):
        return json.dumps(self.__dict__, indent=2)
