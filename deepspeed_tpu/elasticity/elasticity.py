"""Elastic batch-size / chip-count co-design (reference: deepspeed/elasticity/elasticity.py).

Pure arithmetic, identical semantics to the reference: given a list of candidate
micro-batch sizes and a max acceptable global batch size, find the global batch
size that is compatible with the largest number of accelerator counts.  A world
size W is compatible with batch B if there is a micro-batch m in the list with
B % (m * W) == 0 (so gradient_accumulation_steps = B / (m*W) is a whole number).

"Elastic" here is static co-design (not runtime failover): resizing happens by
restart + elastic ZeRO checkpoint repartitioning, same as the reference
(elasticity.py:122-172, compute_elastic_config :240).
"""
import hashlib
import json
from functools import reduce
from math import gcd

from deepspeed_tpu.elasticity.config import (ElasticityConfig, ElasticityConfigError,
                                             ElasticityError,
                                             ElasticityIncompatibleWorldSize)
from deepspeed_tpu.elasticity.constants import (ELASTICITY,
                                                IGNORE_NON_ELASTIC_BATCH_INFO,
                                                IGNORE_NON_ELASTIC_BATCH_INFO_DEFAULT,
                                                LATEST_ELASTICITY_VERSION,
                                                MINIMUM_DEEPSPEED_VERSION)
from deepspeed_tpu.utils.logging import logger

# runtime/constants imported lazily in _compat_check to avoid import cycles


def _lcm(a: int, b: int) -> int:
    return a * b // gcd(a, b)


def _highly_composite_numbers(limit: int):
    """Highly composite numbers (record-setting divisor counts) up to ``limit``.

    The reference ships a hardcoded table (elasticity.py:19-58); we generate the
    same mathematical sequence.  HCNs are products of primorials, so candidates
    are searched over smooth numbers rather than a full sieve.
    """
    primes = [2, 3, 5, 7, 11, 13, 17]

    def divisor_count(exps):
        n = 1
        for e in exps:
            n *= (e + 1)
        return n

    candidates = {}

    def rec(i, value, exps):
        if i == len(primes):
            candidates[value] = divisor_count(exps)
            return
        max_e = exps[i - 1] if i > 0 else 64
        e = 0
        v = value
        while e <= max_e:
            rec(i + 1, v, exps + [e])
            e += 1
            v *= primes[i]
            if v > limit:
                break

    rec(0, 1, [])
    hcns = []
    best = 0
    for n in sorted(candidates):
        if candidates[n] > best:
            best = candidates[n]
            hcns.append(n)
    return hcns


_HCN_CACHE = None


def _hcn_list():
    global _HCN_CACHE
    if _HCN_CACHE is None:
        _HCN_CACHE = _highly_composite_numbers(720720)
    return _HCN_CACHE


def get_candidate_batch_sizes(base_list, max_acceptable_batch_size):
    """For each base size, scale by the largest highly-composite number that
    keeps base*hcn <= max (reference semantics, elasticity.py:61-73).  Note the
    reference quirk: a base larger than max is itself kept as a candidate."""
    candidates = set()
    for base in base_list:
        batch_size = base
        for hcn in _hcn_list():
            scaled = base * hcn
            if scaled > max_acceptable_batch_size:
                break
            batch_size = scaled
        candidates.add(batch_size)
    return list(candidates)


def get_valid_gpus(batch_size, micro_batches, min_valid_gpus, max_valid_gpus):
    """World size w is valid iff some micro-batch mb divides batch_size and w
    divides batch_size//mb (reference: elasticity.py:76-91)."""
    valid = set()
    for mb in micro_batches:
        if batch_size % mb != 0:
            continue
        per_gpu_total = batch_size // mb
        for w in range(1, per_gpu_total + 1):
            if per_gpu_total % w == 0 and min_valid_gpus <= w <= max_valid_gpus:
                valid.add(w)
    return sorted(valid)


def get_best_candidates(candidate_batch_sizes, micro_batches, min_gpus, max_gpus,
                        prefer_larger):
    max_valid_gpus = 0
    valid_gpus = None
    final_batch_size = int(min(micro_batches))
    for batch_size in candidate_batch_sizes:
        current_valid_gpus = get_valid_gpus(batch_size, micro_batches, min_gpus, max_gpus)
        if (len(current_valid_gpus) > max_valid_gpus
                or (len(current_valid_gpus) == max_valid_gpus
                    and ((prefer_larger and batch_size > final_batch_size)
                         or (not prefer_larger and batch_size < final_batch_size)))):
            max_valid_gpus = len(current_valid_gpus)
            valid_gpus = current_valid_gpus
            final_batch_size = batch_size
    return final_batch_size, valid_gpus


def _get_compatible_gpus_v01(micro_batches, max_acceptable_batch_size, min_gpus=None,
                             max_gpus=None, prefer_larger=True):
    min_gpus = min_gpus or 1
    max_gpus = max_gpus or max_acceptable_batch_size // min(micro_batches)
    assert all(mb <= max_acceptable_batch_size for mb in micro_batches), (
        f"All micro batches must be <= max_acceptable_batch_size "
        f"{max_acceptable_batch_size}")
    # bases = each micro batch + the lcm of all of them
    base_list = list(micro_batches) + [reduce(_lcm, micro_batches)]
    candidates = get_candidate_batch_sizes(base_list, max_acceptable_batch_size)
    return get_best_candidates(candidates, micro_batches, min_gpus, max_gpus, prefer_larger)


def _parse_version(version_str: str):
    import re

    m = re.search(r"^(\d+)\.(\d+)(?:\.(\d+))?", version_str)
    if m is None:
        raise ElasticityConfigError(
            f"Unable to parse version {version_str!r}; expected major.minor[.patch]")
    return int(m.group(1)), int(m.group(2)), int(m.group(3) or 0)


def _compatible_version_check(target_version: str):
    """Guard against elastic configs scheduled for an incompatible runtime
    (reference: elasticity.py minimum-version check)."""
    min_v = _parse_version(MINIMUM_DEEPSPEED_VERSION)
    trg_v = _parse_version(target_version)
    if trg_v < min_v:
        raise ElasticityError(
            f"Target version {target_version} is below the minimum version "
            f"{MINIMUM_DEEPSPEED_VERSION} supporting elasticity")
    return True


def elasticity_enabled(ds_config: dict) -> bool:
    if ELASTICITY not in ds_config:
        return False
    return ds_config[ELASTICITY].get("enabled", False)


def ensure_immutable_elastic_config(runtime_elastic_config_dict: dict):
    """Verify the scheduler-time elastic config (env var) matches runtime config.

    Reference behavior (elasticity.py:227-237): a hash of the elastic config is
    stashed in the environment by the scheduler; if present it must match.
    """
    import os
    env_key = "DEEPSPEED_ELASTICITY_CONFIG"
    if env_key in os.environ:
        scheduler_config = json.loads(os.environ[env_key])
        scheduler_hash = hashlib.sha1(
            json.dumps(scheduler_config, sort_keys=True).encode()).hexdigest()
        runtime_hash = hashlib.sha1(
            json.dumps(runtime_elastic_config_dict, sort_keys=True).encode()).hexdigest()
        if scheduler_hash != runtime_hash:
            raise ElasticityConfigError(
                "Elastic config changed between scheduling and runtime; "
                "elastic config is immutable once scheduled")


def compute_elastic_config(ds_config: dict, target_deepspeed_version: str,
                           world_size: int = 0):
    """Compute (final_batch_size, valid_gpus[, micro_batch]) from ds_config.

    With world_size > 0 also returns the micro-batch to use at that world size
    (largest compatible micro-batch when prefer_larger).
    """
    if not isinstance(ds_config, dict):
        raise ValueError(f"Expected ds_config dict, got {type(ds_config)}")
    if ELASTICITY not in ds_config:
        raise ElasticityConfigError(f"'{ELASTICITY}' is missing from config json")
    elastic_config_dict = ds_config[ELASTICITY]
    if not elastic_config_dict.get("enabled", False):
        raise ElasticityConfigError("Elasticity is disabled; set 'enabled': true")
    elastic_config = ElasticityConfig(elastic_config_dict)

    if float(elastic_config.version) > LATEST_ELASTICITY_VERSION:
        raise ElasticityConfigError(
            f"Unsupported elasticity version {elastic_config.version}, "
            f"latest is {LATEST_ELASTICITY_VERSION}")

    _compatible_version_check(target_deepspeed_version)

    if float(elastic_config.version) == 0.1:
        final_batch_size, valid_gpus = _get_compatible_gpus_v01(
            micro_batches=elastic_config.micro_batches,
            max_acceptable_batch_size=elastic_config.max_acceptable_batch_size,
            min_gpus=elastic_config.min_gpus,
            max_gpus=elastic_config.max_gpus,
            prefer_larger=elastic_config.prefer_larger_batch_size)
        final_batch_size = int(final_batch_size)
    else:
        raise NotImplementedError(
            f"Unable to find elastic logic for version: {elastic_config.version}")

    if world_size > 0:
        if world_size not in valid_gpus:
            raise ElasticityIncompatibleWorldSize(
                f"World size {world_size} is not valid for this elastic config; "
                f"valid world sizes: {valid_gpus}")
        # pick the largest micro batch that divides the per-replica batch
        micro_batch = None
        per_replica = final_batch_size // world_size
        for mbsz in sorted(set(elastic_config.micro_batches), reverse=True):
            if per_replica % mbsz == 0:
                micro_batch = mbsz
                break
        assert micro_batch is not None, (
            f"Unable to find divisible micro batch: world_size={world_size}, "
            f"final_batch_size={final_batch_size}, micro_batches="
            f"{elastic_config.micro_batches}")
        return final_batch_size, valid_gpus, micro_batch

    return final_batch_size, valid_gpus
