"""deepspeed_tpu — TPU-native training framework with the DeepSpeed v0.3.11 API.

Public surface parity with reference deepspeed/__init__.py: ``initialize()``,
``add_config_arguments()``, ``init_distributed()``, engine/module exports.
Compute path is JAX/XLA/Pallas over a named-axis device mesh.
"""
from deepspeed_tpu.version import __reference_version__, __version__

# Heavier modules (engine, models) are imported lazily below so that pure-logic
# users (config math, schedules, launcher CLI) don't pay the jax import cost.

__git_hash__ = None
__git_branch__ = None


def initialize(args=None, model=None, optimizer=None, model_parameters=None,
               training_data=None, lr_scheduler=None, mpu=None,
               dist_init_required=None, collate_fn=None, config_params=None):
    """Initialize the engine.  Mirrors reference deepspeed/__init__.py:50-139.

    Returns a tuple of (engine, optimizer, training_dataloader, lr_scheduler).
    """
    from deepspeed_tpu.runtime.engine import DeepSpeedEngine
    from deepspeed_tpu.runtime.pipe.engine import PipelineEngine
    from deepspeed_tpu.runtime.pipe.module import PipelineModule

    if isinstance(model, PipelineModule):
        engine = PipelineEngine(args=args, model=model, optimizer=optimizer,
                                model_parameters=model_parameters,
                                training_data=training_data,
                                lr_scheduler=lr_scheduler, mpu=model.mpu() if mpu is None else mpu,
                                dist_init_required=dist_init_required,
                                collate_fn=collate_fn, config_params=config_params)
    else:
        engine = DeepSpeedEngine(args=args, model=model, optimizer=optimizer,
                                 model_parameters=model_parameters,
                                 training_data=training_data,
                                 lr_scheduler=lr_scheduler, mpu=mpu,
                                 dist_init_required=dist_init_required,
                                 collate_fn=collate_fn, config_params=config_params)

    return engine, engine.optimizer, engine.training_dataloader, engine.lr_scheduler


def add_config_arguments(parser):
    """Add --deepspeed / --deepspeed_config argparse flags
    (reference deepspeed/__init__.py:142-190)."""
    group = parser.add_argument_group("DeepSpeed", "DeepSpeed configurations")
    group.add_argument("--deepspeed", default=False, action="store_true",
                       help="Enable DeepSpeed (helper flag for user code; "
                            "DeepSpeed=True if flag is present)")
    group.add_argument("--deepspeed_config", default=None, type=str,
                       help="DeepSpeed json configuration file.")
    group.add_argument("--deepscale", default=False, action="store_true",
                       help="Deprecated enable DeepSpeed (helper flag)")
    group.add_argument("--deepscale_config", default=None, type=str,
                       help="Deprecated DeepSpeed json configuration file.")
    group.add_argument("--deepspeed_mpi", default=False, action="store_true",
                       help="Run via MPI; discover rank/world from the MPI environment.")
    return parser


def init_distributed(dist_backend=None, auto_mpi_discovery=True,
                     distributed_port=29500, verbose=True):
    from deepspeed_tpu.utils.distributed import init_distributed as _init

    return _init(dist_backend=dist_backend, auto_mpi_discovery=auto_mpi_discovery,
                 distributed_port=distributed_port, verbose=verbose)


def __getattr__(name):
    # Lazy exports that pull in jax/flax.
    if name == "DeepSpeedEngine":
        from deepspeed_tpu.runtime.engine import DeepSpeedEngine
        return DeepSpeedEngine
    if name == "PipelineEngine":
        from deepspeed_tpu.runtime.pipe.engine import PipelineEngine
        return PipelineEngine
    if name == "PipelineModule":
        from deepspeed_tpu.runtime.pipe.module import PipelineModule
        return PipelineModule
    if name == "DeepSpeedConfig":
        from deepspeed_tpu.runtime.config import DeepSpeedConfig
        return DeepSpeedConfig
    if name == "DeepSpeedTransformerLayer":
        from deepspeed_tpu.ops.transformer import DeepSpeedTransformerLayer
        return DeepSpeedTransformerLayer
    if name == "DeepSpeedTransformerConfig":
        from deepspeed_tpu.ops.transformer import DeepSpeedTransformerConfig
        return DeepSpeedTransformerConfig
    if name == "checkpointing":
        from deepspeed_tpu.runtime import activation_checkpointing
        return activation_checkpointing
    if name == "moe":
        from deepspeed_tpu import moe
        return moe
    raise AttributeError(f"module 'deepspeed_tpu' has no attribute {name!r}")
