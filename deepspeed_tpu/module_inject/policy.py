"""Injection policies + the generic param-tree walker.

Reference behavior: deepspeed/module_inject/replace_module.py:93-161 walks a
torch module tree and swaps every instance of a policy's `orig_class` for
the fused layer (and back). Models here are (module, params) pairs, so the
walker operates on the PARAM tree: a policy declares how to recognize one
layer subtree by name and how to map its params onto the fused layer's (and
back). New architectures plug in by registering a policy instead of editing
the walker — the reference's policy-class extension point.
"""
import re

import numpy as np


class LayerPolicy:
    """One injectable layer family.

    match(name) -> layer index (int) or None;
    inject(subtree) -> fused-layer params;
    revert(fused_params) -> original subtree;
    out_name(i) -> the replaced layer's name in the output tree.
    """

    layer_pattern = r"^layer_?(\d+)$"
    out_prefix = "layer_"

    def match(self, name):
        m = re.match(self.layer_pattern, str(name))
        return int(m.group(1)) if m else None

    def out_name(self, i):
        return f"{self.out_prefix}{i}"

    def inject(self, subtree):  # pragma: no cover - abstract
        raise NotImplementedError

    def revert(self, subtree, hidden_size):  # pragma: no cover - abstract
        raise NotImplementedError


class HFBertLayerPolicy(LayerPolicy):
    """HF-Flax BertLayer <-> DeepSpeedTransformerLayer (the reference's
    HFBertLayerPolicy analog; fuses q/k/v into the qkv parameter)."""

    def __init__(self, preln=False):
        self.preln = preln

    def inject(self, subtree):
        from deepspeed_tpu.module_inject.replace_module import (
            inject_bert_layer_params)

        return inject_bert_layer_params(subtree, preln=self.preln)

    def revert(self, subtree, hidden_size):
        from deepspeed_tpu.module_inject.replace_module import (
            revert_bert_layer_params)

        return revert_bert_layer_params(subtree, hidden_size)


POLICY_REGISTRY = {"bert": HFBertLayerPolicy}


def register_policy(name, policy_cls):
    POLICY_REGISTRY[name] = policy_cls


def replace_module_params(params, policy: LayerPolicy, recurse=True):
    """Walk a nested param dict; wherever a child name matches the policy's
    layer pattern, replace that subtree via policy.inject. Non-matching
    dicts are recursed (reference replace_module walks arbitrary depth).

    Returns (new_tree, n_replaced)."""
    n = 0

    def walk(tree):
        nonlocal n
        if not isinstance(tree, dict):
            return tree
        out = {}
        for name, sub in tree.items():
            idx = policy.match(name) if isinstance(sub, dict) else None
            if idx is not None:
                out[policy.out_name(idx)] = policy.inject(sub)
                n += 1
            elif recurse:
                out[name] = walk(sub)
            else:
                out[name] = sub
        return out

    new = walk(params)
    return new, n


def _t(x):
    """HF-Flax GPT-2 stores Conv1D kernels (out, in); ours are flax Dense
    (in, out)."""
    x = np.asarray(x)
    return x.T if x.ndim == 2 else x


def _pad_rows(arr, multiple):
    """Grow dim 0 with zero rows to the next `multiple` (MXU vocab
    alignment) — shared by both HF loaders; the padded rows are inert
    because the models slice/mask logits back to the true vocab."""
    from deepspeed_tpu.models.api import pad_to_multiple

    target = pad_to_multiple(arr.shape[0], multiple)
    if target == arr.shape[0]:
        return arr
    pad_shape = (target - arr.shape[0],) + arr.shape[1:]
    return np.concatenate([arr, np.zeros(pad_shape, arr.dtype)])


def load_hf_bert_params(hf_params, config=None, pad_vocab_multiple=128):
    """transformers FlaxBertForPreTraining params -> models/bert
    BertForPreTraining params (fused DeepSpeedTransformerLayer encoder):
    bring pretrained HF BERT weights into this framework. HF BERT is
    post-LN, so pair with BertConfig(pre_layer_norm=False). Word embedding
    and MLM bias grow zero pad rows to padded_vocab_size (MXU alignment;
    logits are sliced back so the rows are inert).

    Mirrors load_hf_gpt2_params below; the per-layer mapping is
    replace_module.inject_bert_layer_params (the reference's
    HFBertLayerPolicy, deepspeed/module_inject/inject.py:8-58)."""
    from deepspeed_tpu.module_inject.replace_module import replace_bert_params

    if config is not None:
        pad_vocab_multiple = config.pad_vocab_multiple
    if "cls" not in hf_params:
        # unlike GPT-2 (everything under 'transformer'), the MLM/NSP heads
        # live OUTSIDE the 'bert' subtree — a bare subtree cannot be loaded
        raise KeyError(
            "load_hf_bert_params needs the FULL FlaxBertForPreTraining "
            "params ({'bert': ..., 'cls': ...}); the 'cls' prediction "
            "heads are missing — pass hf_model.params, not a subtree")
    t = hf_params["bert"]
    cls = hf_params["cls"]
    emb = t["embeddings"]
    word = _pad_rows(np.asarray(emb["word_embeddings"]["embedding"]),
                     pad_vocab_multiple)
    mlm_bias = _pad_rows(np.asarray(cls["predictions"]["bias"]),
                         pad_vocab_multiple)
    transform = cls["predictions"]["transform"]
    out = {
        "embeddings": {
            "word_embeddings": word,
            "position_embeddings": np.asarray(
                emb["position_embeddings"]["embedding"]),
            "token_type_embeddings": np.asarray(
                emb["token_type_embeddings"]["embedding"]),
            "ln": {"scale": np.asarray(emb["LayerNorm"]["scale"]),
                   "bias": np.asarray(emb["LayerNorm"]["bias"])},
        },
        # HF flax keys encoder layers by bare index ("0", "1", ...)
        "encoder": replace_bert_params(t["encoder"]["layer"],
                                       layer_pattern=r"^(\d+)$"),
        "mlm_transform": {
            "kernel": np.asarray(transform["dense"]["kernel"]),
            "bias": np.asarray(transform["dense"]["bias"])},
        "mlm_ln": {"scale": np.asarray(transform["LayerNorm"]["scale"]),
                   "bias": np.asarray(transform["LayerNorm"]["bias"])},
        "mlm_bias": mlm_bias,
        "pooler": {"kernel": np.asarray(t["pooler"]["dense"]["kernel"]),
                   "bias": np.asarray(t["pooler"]["dense"]["bias"])},
        "nsp": {"kernel": np.asarray(cls["seq_relationship"]["kernel"]),
                "bias": np.asarray(cls["seq_relationship"]["bias"])},
    }
    return out


def load_hf_gpt2_params(hf_params, config=None, pad_vocab_multiple=128):
    """transformers FlaxGPT2LMHeadModel params -> models/gpt2.GPT2LMHead
    params (non-scan layout): bring pretrained HF GPT-2 weights into this
    framework. Layer subtrees keep their structure (ln_1/attn/ln_2/mlp);
    2D kernels transpose from HF's (out, in) Conv1D layout; wte grows zero
    pad rows up to GPT2Config.padded_vocab_size (MXU lane alignment — the
    model slices/masks logits back, so the rows are inert).

    Pass the target GPT2Config so the loader pads to EXACTLY the shape the
    model will init (a config with pad_vocab_multiple=0 or a non-default
    multiple must not meet a 128-padded table); pad_vocab_multiple is the
    fallback when no config is given."""
    if config is not None:
        pad_vocab_multiple = config.pad_vocab_multiple
    t = hf_params.get("transformer", hf_params)
    wte = _pad_rows(np.asarray(t["wte"]["embedding"]), pad_vocab_multiple)
    out = {
        "wte": wte,
        "wpe": np.asarray(t["wpe"]["embedding"]),
        "ln_f": {k: np.asarray(v) for k, v in t["ln_f"].items()},
    }
    for i, layer in t["h"].items():
        out[f"h_{int(i)}"] = {
            "ln_1": {k: np.asarray(v) for k, v in layer["ln_1"].items()},
            "ln_2": {k: np.asarray(v) for k, v in layer["ln_2"].items()},
            "attn": {
                "c_attn": {"kernel": _t(layer["attn"]["c_attn"]["kernel"]),
                           "bias": np.asarray(layer["attn"]["c_attn"]["bias"])},
                "c_proj": {"kernel": _t(layer["attn"]["c_proj"]["kernel"]),
                           "bias": np.asarray(layer["attn"]["c_proj"]["bias"])},
            },
            "mlp": {
                "c_fc": {"kernel": _t(layer["mlp"]["c_fc"]["kernel"]),
                         "bias": np.asarray(layer["mlp"]["c_fc"]["bias"])},
                "c_proj": {"kernel": _t(layer["mlp"]["c_proj"]["kernel"]),
                           "bias": np.asarray(layer["mlp"]["c_proj"]["bias"])},
            },
        }
    return out
