"""module_inject — swap a model's encoder layers for the fused layer.

Reference behavior: deepspeed/module_inject/{inject.py:6-107,
replace_module.py:6-181}: walk a HF BERT model, replace each BertLayer with
DeepSpeedTransformerLayer, concatenating q/k/v weights into the fused qkv
parameter; `revert_module` splits them back.

TPU formulation: models are (module, params) pairs, so injection is pure
param surgery — `inject_bert_layer_params` maps one HF-Flax-style BertLayer
param subtree onto a DeepSpeedTransformerLayer subtree (fusing q/k/v),
`revert_bert_layer_params` inverts it, and `replace_bert_params` applies the
mapping across a whole encoder. The swapped-in module is the same
DeepSpeedTransformerLayer the reference injects; on TPU the fusion win comes
from XLA compiling the layer as one program (flash attention + fused
LN/GeLU/bias), so "injection" only needs to rearrange parameters.
"""
import re

import numpy as np


def _cat(*arrays, axis):
    return np.concatenate([np.asarray(a) for a in arrays], axis=axis)


def inject_bert_layer_params(hf_layer, preln=False):
    """HF-Flax BertLayer params -> DeepSpeedTransformerLayer params.

    hf_layer keys (HF flax naming):
      attention/self/{query,key,value}/{kernel,bias},
      attention/output/dense/{kernel,bias},
      attention/output/LayerNorm/{scale,bias},
      intermediate/dense/{kernel,bias},
      output/dense/{kernel,bias}, output/LayerNorm/{scale,bias}
    Kernels are (in, out) as flax stores them (the reference concatenates
    torch (out, in) weights on dim 0, inject.py:41-43 — here the fused qkv
    concatenates on the OUT dim, axis 1).
    """
    att = hf_layer["attention"]
    qkv_kernel = _cat(att["self"]["query"]["kernel"],
                      att["self"]["key"]["kernel"],
                      att["self"]["value"]["kernel"], axis=1)
    qkv_bias = _cat(att["self"]["query"]["bias"],
                    att["self"]["key"]["bias"],
                    att["self"]["value"]["bias"], axis=0)
    return {"body": {
        "qkv": {"kernel": qkv_kernel, "bias": qkv_bias},
        "attn_out": {"kernel": np.asarray(att["output"]["dense"]["kernel"]),
                     "bias": np.asarray(att["output"]["dense"]["bias"])},
        "attn_ln": {"scale": np.asarray(att["output"]["LayerNorm"]["scale"]),
                    "bias": np.asarray(att["output"]["LayerNorm"]["bias"])},
        "ffn_inter": {"kernel": np.asarray(
            hf_layer["intermediate"]["dense"]["kernel"]),
            "bias": np.asarray(hf_layer["intermediate"]["dense"]["bias"])},
        "ffn_out": {"kernel": np.asarray(hf_layer["output"]["dense"]["kernel"]),
                    "bias": np.asarray(hf_layer["output"]["dense"]["bias"])},
        "ffn_ln": {"scale": np.asarray(hf_layer["output"]["LayerNorm"]["scale"]),
                   "bias": np.asarray(hf_layer["output"]["LayerNorm"]["bias"])},
    }}


def revert_bert_layer_params(ds_layer, hidden_size):
    """DeepSpeedTransformerLayer params -> HF-Flax BertLayer params
    (reference replace_module.py revert path, :93-161)."""
    body = ds_layer["body"]
    qkv_k = np.asarray(body["qkv"]["kernel"])
    qkv_b = np.asarray(body["qkv"]["bias"])
    q_k, k_k, v_k = np.split(qkv_k, 3, axis=1)
    q_b, k_b, v_b = np.split(qkv_b, 3, axis=0)
    return {
        "attention": {
            "self": {"query": {"kernel": q_k, "bias": q_b},
                     "key": {"kernel": k_k, "bias": k_b},
                     "value": {"kernel": v_k, "bias": v_b}},
            "output": {
                "dense": {"kernel": np.asarray(body["attn_out"]["kernel"]),
                          "bias": np.asarray(body["attn_out"]["bias"])},
                "LayerNorm": {"scale": np.asarray(body["attn_ln"]["scale"]),
                              "bias": np.asarray(body["attn_ln"]["bias"])}}},
        "intermediate": {"dense": {
            "kernel": np.asarray(body["ffn_inter"]["kernel"]),
            "bias": np.asarray(body["ffn_inter"]["bias"])}},
        "output": {
            "dense": {"kernel": np.asarray(body["ffn_out"]["kernel"]),
                      "bias": np.asarray(body["ffn_out"]["bias"])},
            "LayerNorm": {"scale": np.asarray(body["ffn_ln"]["scale"]),
                          "bias": np.asarray(body["ffn_ln"]["bias"])}},
    }


def replace_bert_params(hf_params, layer_pattern=r"^layer_?(\d+)$",
                        preln=False):
    """Map every matching layer subtree of an HF-Flax encoder param dict
    (e.g. params['encoder']['layer']) through inject_bert_layer_params.

    Returns {our_layer_name: ds_params} with names 'layer_<i>' matching
    models/bert.py BertEncoder."""
    out = {}
    for name, sub in hf_params.items():
        m = re.match(layer_pattern, str(name))
        if m:
            out[f"layer_{int(m.group(1))}"] = inject_bert_layer_params(
                sub, preln=preln)
    if not out:
        raise ValueError(
            f"no layers matched pattern {layer_pattern!r} among "
            f"{sorted(map(str, hf_params.keys()))[:8]}")
    return out
