from deepspeed_tpu.module_inject.replace_module import (
    inject_bert_layer_params, replace_bert_params, revert_bert_layer_params)
from deepspeed_tpu.module_inject.policy import (
    HFBertLayerPolicy, LayerPolicy, POLICY_REGISTRY, load_hf_bert_params,
    load_hf_gpt2_params, register_policy, replace_module_params)
