from deepspeed_tpu.module_inject.replace_module import (
    inject_bert_layer_params, replace_bert_params, revert_bert_layer_params)
