"""Per-node launcher — spawns the training process and babysits it.

Reference behavior: deepspeed/launcher/launch.py:67-171 (decode base64
world-info, set RANK/LOCAL_RANK/WORLD_SIZE/MASTER_*, one process per GPU,
signal-propagating babysitter).

TPU adaptation: ONE training process per host (it owns every local chip),
so rank == node_rank and world_size == number of hosts. LOCAL_RANK is set
to 0 for script compatibility.
"""
import argparse
import base64
import json
import os
import signal
import subprocess
import sys

from deepspeed_tpu.utils.logging import logger


def parse_args(args=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--world_info", type=str, required=True,
                        help="base64 json {host: [slot...]}")
    parser.add_argument("--node_rank", type=int, default=0)
    parser.add_argument("--master_addr", type=str, default="127.0.0.1")
    parser.add_argument("--master_port", type=int, default=29500)
    parser.add_argument("user_script", type=str)
    parser.add_argument("user_args", nargs=argparse.REMAINDER)
    return parser.parse_args(args=args)


def decode_world_info(encoded):
    return json.loads(base64.urlsafe_b64decode(encoded.encode()).decode())


def main(args=None):
    args = parse_args(args)
    world_info = decode_world_info(args.world_info)
    hosts = list(world_info.keys())
    world_size = len(hosts)      # one process per host on TPU
    node_rank = args.node_rank
    assert 0 <= node_rank < max(1, world_size), \
        f"node_rank {node_rank} out of range for {world_size} hosts"

    env = os.environ.copy()
    env["RANK"] = str(node_rank)
    env["LOCAL_RANK"] = "0"
    env["WORLD_SIZE"] = str(world_size)
    env["MASTER_ADDR"] = args.master_addr
    env["MASTER_PORT"] = str(args.master_port)
    env["DSTPU_NODE_SLOTS"] = str(len(world_info.get(hosts[node_rank], [0]))
                                  if world_size else 1)

    cmd = [sys.executable, "-u", args.user_script] + args.user_args
    logger.info(f"launch: rank={node_rank}/{world_size} cmd={cmd}")
    process = subprocess.Popen(cmd, env=env)

    # babysitter: forward signals, kill on child failure
    # (reference launch.py:131-165)
    def sig_handler(signum, frame):
        process.send_signal(signum)

    signal.signal(signal.SIGTERM, sig_handler)
    signal.signal(signal.SIGINT, sig_handler)
    process.wait()
    if process.returncode != 0:
        logger.error(f"training process exited with code "
                     f"{process.returncode}")
    return process.returncode


if __name__ == "__main__":
    sys.exit(main())
