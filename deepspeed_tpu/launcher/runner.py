"""`ds` runner — multi-host TPU launch front-end.

Reference behavior: deepspeed/launcher/runner.py:115-360 (hostfile parse
`hostname slots=N`, --include/--exclude filters, base64 world-info, pdsh/
mpirun fan-out, .deepspeed_env forwarding).

TPU adaptation: ONE process per host owns all local chips (SURVEY §2.10) —
"slots" counts chips for resource accounting, but the spawned world has one
rank per host. Rendezvous is MASTER_ADDR/MASTER_PORT ->
jax.distributed.initialize (utils/distributed.py).
"""
import argparse
import base64
import json
import os
import subprocess
import sys
from collections import OrderedDict
from shlex import quote

from deepspeed_tpu.utils.logging import logger

DLTS_HOSTFILE = "/job/hostfile"
EXPORT_ENVS = ["PYTHONPATH", "PATH", "LD_LIBRARY_PATH", "JAX_PLATFORMS",
               "XLA_FLAGS", "LIBTPU_INIT_ARGS", "TPU_NAME"]
DEEPSPEED_ENVIRONMENT_NAME = ".deepspeed_env"
DEEPSPEED_ENVIRONMENT_PATHS = [".", os.path.expanduser("~")]


def parse_args(args=None):
    parser = argparse.ArgumentParser(
        description="deepspeed_tpu launcher: run a training script across "
                    "TPU hosts")
    parser.add_argument("-H", "--hostfile", type=str, default=DLTS_HOSTFILE,
                        help="hostfile of 'hostname slots=N' lines")
    parser.add_argument("-i", "--include", type=str, default="",
                        help="host[:slot[,slot]][@host...] inclusion filter")
    parser.add_argument("-e", "--exclude", type=str, default="",
                        help="same syntax exclusion filter")
    parser.add_argument("--num_nodes", type=int, default=-1,
                        help="limit to first N nodes")
    parser.add_argument("--num_gpus", "--num_chips", type=int, default=-1,
                        dest="num_gpus", help="chips per node to use")
    parser.add_argument("--master_port", type=int, default=29500)
    parser.add_argument("--master_addr", type=str, default="")
    parser.add_argument("--launcher", type=str, default="pdsh",
                        choices=["pdsh", "openmpi", "ssh"],
                        help="multi-node fan-out backend")
    parser.add_argument("--launcher_args", type=str, default="")
    parser.add_argument("--force_multi", action="store_true")
    parser.add_argument("user_script", type=str)
    parser.add_argument("user_args", nargs=argparse.REMAINDER)
    return parser.parse_args(args=args)


def fetch_hostfile(hostfile_path):
    """hostfile -> OrderedDict{hostname: slot_count}
    (reference runner.py:115-145)."""
    if not os.path.isfile(hostfile_path):
        logger.warning(f"Unable to find hostfile at {hostfile_path}; "
                       f"proceeding with localhost only")
        return None
    resource_pool = OrderedDict()
    with open(hostfile_path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            try:
                hostname, slots = line.split()
                key, slot_count = slots.split("=")
                if key != "slots":
                    raise ValueError(key)
                slot_count = int(slot_count)
            except ValueError:
                raise ValueError(
                    f"Hostfile is not formatted correctly, unable to parse "
                    f"line: {line!r} (expected 'hostname slots=N')")
            if hostname in resource_pool:
                raise ValueError(
                    f"Hostfile contains duplicate hosts: {hostname}")
            resource_pool[hostname] = slot_count
    if not resource_pool:
        raise ValueError("Hostfile is empty or formatted incorrectly")
    return resource_pool


def _parse_filter(spec):
    """'host1:0,1@host2' -> {host: [slots] or []} (reference :157-196)."""
    mapping = {}
    for part in spec.split("@"):
        part = part.strip()
        if not part:
            continue
        if ":" in part:
            host, slots = part.split(":")
            mapping[host] = [int(s) for s in slots.split(",")]
        else:
            mapping[part] = []
    return mapping


def parse_resource_filter(host_info, include_str="", exclude_str=""):
    """Apply --include/--exclude (reference runner.py:146-246).
    Only one of the two may be set."""
    if include_str and exclude_str:
        raise ValueError("include_str and exclude_str are mutually exclusive")
    if not include_str and not exclude_str:
        return host_info

    filtered = OrderedDict()
    if include_str:
        for host, slots in _parse_filter(include_str).items():
            if host not in host_info:
                raise ValueError(f"Hostname '{host}' not found in hostfile")
            for s in slots:
                if s >= host_info[host]:
                    raise ValueError(f"No slot '{s}' specified on host "
                                     f"'{host}'")
            filtered[host] = len(slots) if slots else host_info[host]
        return filtered

    excl = _parse_filter(exclude_str)
    for host, count in host_info.items():
        if host not in excl:
            filtered[host] = count
            continue
        slots = excl[host]
        if not slots:
            continue   # whole host excluded
        for s in slots:
            if s >= count:
                raise ValueError(f"No slot '{s}' specified on host '{host}'")
        remaining = count - len(set(slots))
        if remaining > 0:
            filtered[host] = remaining
    if not filtered:
        raise ValueError("No hosts left after exclusion filter")
    return filtered


def encode_world_info(resource_pool):
    world_info = {host: list(range(slots))
                  for host, slots in resource_pool.items()}
    return base64.urlsafe_b64encode(
        json.dumps(world_info).encode()).decode()


def collect_env_exports():
    """EXPORT_ENVS + .deepspeed_env entries (reference :296-320)."""
    exports = {}
    for var in EXPORT_ENVS:
        if var in os.environ:
            exports[var] = os.environ[var]
    for path in DEEPSPEED_ENVIRONMENT_PATHS:
        env_file = os.path.join(path, DEEPSPEED_ENVIRONMENT_NAME)
        if os.path.isfile(env_file):
            with open(env_file) as f:
                for line in f:
                    line = line.strip()
                    if line and "=" in line:
                        key, val = line.split("=", 1)
                        exports[key] = val
    return exports


def main(args=None):
    args = parse_args(args)
    resource_pool = fetch_hostfile(args.hostfile)

    if resource_pool is None:
        # single node: spawn launch.py locally
        resource_pool = OrderedDict(localhost=args.num_gpus
                                    if args.num_gpus > 0 else 1)
        active = resource_pool
        multi_node = False
    else:
        active = parse_resource_filter(resource_pool, args.include,
                                       args.exclude)
        if args.num_nodes > 0:
            active = OrderedDict(list(active.items())[:args.num_nodes])
        multi_node = args.force_multi or len(active) > 1

    master_addr = args.master_addr
    if not master_addr:
        if multi_node:
            first = next(iter(active))
            try:
                out = subprocess.run(
                    ["ssh", first, "hostname", "-I"], capture_output=True,
                    text=True, timeout=30, check=True)
                master_addr = out.stdout.split()[0]
            except (OSError, subprocess.SubprocessError):
                master_addr = first
        else:
            master_addr = "127.0.0.1"

    world_info = encode_world_info(active)
    launch_cmd = [sys.executable, "-u", "-m", "deepspeed_tpu.launcher.launch",
                  f"--world_info={world_info}",
                  f"--master_addr={master_addr}",
                  f"--master_port={args.master_port}"]

    if not multi_node:
        cmd = launch_cmd + ["--node_rank=0", args.user_script] + args.user_args
        logger.info(f"cmd = {' '.join(map(str, cmd))}")
        result = subprocess.run(cmd)
        return result.returncode

    from deepspeed_tpu.launcher.multinode_runner import (OpenMPIRunner,
                                                         PDSHRunner, SSHRunner)

    runner_cls = {"pdsh": PDSHRunner, "openmpi": OpenMPIRunner,
                  "ssh": SSHRunner}[args.launcher]
    runner = runner_cls(args, world_info)
    if not runner.backend_exists():
        raise RuntimeError(f"launcher backend {args.launcher} not available "
                           f"on this host")
    env = collect_env_exports()
    cmd = runner.get_cmd(env, active)
    logger.info(f"cmd = {' '.join(map(str, cmd))}")
    result = subprocess.run(cmd, env={**os.environ, **env})
    return result.returncode


if __name__ == "__main__":
    sys.exit(main())
