"""Multi-node fan-out backends (reference
deepspeed/launcher/multinode_runner.py:35-189: PDSH / OpenMPI / MVAPICH).

Each runner builds the command that starts launcher.launch on every host
with its node_rank. MVAPICH (CUDA-specific) is replaced with a plain SSH
runner, the common fallback on TPU-VM fleets.
"""
import os
import shutil
import sys
from abc import ABC, abstractmethod
from shlex import quote


class MultiNodeRunner(ABC):
    def __init__(self, args, world_info_base64):
        self.args = args
        self.world_info_base64 = world_info_base64
        self.user_arguments = list(args.user_args)
        self.user_script = args.user_script

    @abstractmethod
    def backend_exists(self):
        ...

    @abstractmethod
    def get_cmd(self, environment, active_resources):
        ...

    @property
    def name(self):
        return self.__class__.__name__.replace("Runner", "").lower()

    def _launch_args(self, node_rank):
        return [sys.executable, "-u", "-m", "deepspeed_tpu.launcher.launch",
                f"--world_info={self.world_info_base64}",
                f"--node_rank={node_rank}",
                f"--master_addr={getattr(self.args, 'master_addr', '')}",
                f"--master_port={self.args.master_port}"]


class PDSHRunner(MultiNodeRunner):
    """pdsh fanout (reference :35-76); node_rank comes from %n."""

    def backend_exists(self):
        return shutil.which("pdsh") is not None

    def get_cmd(self, environment, active_resources):
        environment = dict(environment)
        environment["PDSH_RCMD_TYPE"] = "ssh"
        hosts = ",".join(active_resources.keys())
        exports = " ".join(f"export {k}={quote(v)};"
                           for k, v in environment.items())
        # %n is pdsh's 0-based position of the host in the -w list
        inner = (f"{exports} cd {os.path.abspath('.')}; "
                 + " ".join(map(quote, self._launch_args("%n")
                                + [self.user_script]
                                + self.user_arguments)))
        # un-quote the %n placeholder so pdsh substitutes it
        inner = inner.replace(quote("--node_rank=%n"), "--node_rank=%n")
        return ["pdsh", "-S", "-f", "1024", "-w", hosts, inner]


class OpenMPIRunner(MultiNodeRunner):
    """mpirun fanout (reference :78-116); node_rank from
    OMPI_COMM_WORLD_RANK, resolved inside launch via env."""

    def backend_exists(self):
        return shutil.which("mpirun") is not None

    def get_cmd(self, environment, active_resources):
        total = len(active_resources)
        hosts = ",".join(f"{h}:1" for h in active_resources)
        cmd = ["mpirun", "-n", str(total), "--host", hosts,
               "--mca", "btl", "^openib"]
        for k, v in environment.items():
            cmd += ["-x", f"{k}={v}"]
        # under mpirun each rank IS the per-node process; skip launch.py and
        # rely on utils/distributed mpi_discovery for rendezvous
        cmd += [sys.executable, "-u", self.user_script] + self.user_arguments
        return cmd


class SSHRunner(MultiNodeRunner):
    """Plain ssh loop — no extra tooling required (replaces the reference's
    MVAPICH runner for TPU fleets)."""

    def backend_exists(self):
        return shutil.which("ssh") is not None

    def get_cmd(self, environment, active_resources):
        exports = " ".join(f"export {k}={quote(v)};"
                           for k, v in environment.items())
        script = []
        for rank, host in enumerate(active_resources):
            inner = (f"{exports} cd {os.path.abspath('.')}; "
                     + " ".join(map(quote, self._launch_args(rank)
                                    + [self.user_script]
                                    + self.user_arguments)))
            script.append(f"ssh {host} {quote(inner)} &")
        script.append("wait")
        return ["bash", "-c", "\n".join(script)]
