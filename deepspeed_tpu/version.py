__version__ = "0.1.0"
# parity target: reference DeepSpeed snapshot 0.3.11 (version.txt:1)
__reference_version__ = "0.3.11"
git_hash = None
git_branch = None
